"""Asynchronous, crash-consistent checkpointing with exact-step resume.

``CheckpointManager`` turns the repo's existing ingredients — the zip model
format (utils/serialization.py), host snapshots safe under buffer donation
(earlystopping/savers.py's ``device_get`` discipline) and the watchdog's
bounded-deadline pattern (parallel/watchdog.py) — into durable, low-overhead,
resumable training:

- **Snapshot on the training thread, write on a worker thread.** ``save``
  copies params + updater state + PRNG key + step/epoch counters to host
  (``jax.device_get`` — safe w.r.t. ``donate_argnums``) and returns; a
  bounded queue hands the snapshot to a writer thread, so the step loop
  never blocks on disk. ``async_write=False`` degrades to synchronous
  commits (deterministic tests, worst-case-overhead benching).
- **Atomic, journaled commits through pluggable storage.** Bytes are
  committed atomically by a :class:`~deeplearning4j_tpu.checkpoint.storage.
  StorageBackend` (local tmp/ + fsync + rename by default; GCS-style
  object stores via ``ObjectStoreBackend``; transient-fault retries via
  ``RetryingBackend``), then the entry (with the payload's sha256) is
  journaled into a checksummed ``manifest.json`` (checkpoint/manifest.py).
  A torn write is detected, and ``restore_latest`` falls back to the last
  complete checkpoint — identically through any backend.
- **Retention.** ``keep_last=N`` bounds disk; ``keep_best`` ("min"/"max"
  over the ``metric`` passed to ``save``) pins the best checkpoint outside
  that window.
- **Triggers.** ``save_every_n_steps`` / ``save_every_secs`` are evaluated
  by ``step_end``, which ``fit(..., checkpoint_manager=)`` calls after
  every optimizer step on MultiLayerNetwork, ComputationGraph,
  ParallelWrapper and ClusterTrainer.
- **Exact-step resume.** A checkpoint records ``batch_in_epoch``; the model
  ``restore_latest`` returns carries a :class:`ResumeState`, and the next
  ``fit`` treats ``num_epochs`` as the run's TOTAL target — it skips the
  already-consumed batches of the interrupted epoch and continues the
  restored rng split chain, so resume is BITWISE-identical to the
  uninterrupted run (asserted in tests/test_checkpoint.py via
  checkpoint/faults.py's FaultInjector).
- **Multi-host.** Only process 0 writes; every ``save`` point is a
  collective barrier bounded by a ``CollectiveWatchdog`` deadline, so a
  dead peer surfaces as a diagnostic timeout instead of a silent hang.
  The default (whole-zip) format needs params process-0 addressable;
  ``sharded=True`` removes that restriction: every host writes its OWN
  shard of the state (checkpoint/sharded.py), the set is journaled as one
  manifest entry (per-shard sha256) only after every shard is durable,
  and restore reassembles the full state on any world size — a 4-worker
  checkpoint restores into a 3-worker (or 1-worker) job, the N→M
  reshard-on-restore the elastic layer (parallel/elastic.py) builds on.

The manager also implements the early-stopping saver protocol
(``save_best_model`` / ``save_latest_model`` / ``get_best_model``), so it
drops in as ``EarlyStoppingConfiguration.model_saver`` — best models become
durable, checksummed checkpoints instead of bare zips.

Reference analogue: CheckpointListener.java (periodic in-place saves, no
journal, no atomicity, no resume-to-exact-step) — superseded here.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import os
import queue
import threading
import time
from typing import List, Optional

log = logging.getLogger(__name__)


class CheckpointError(RuntimeError):
    """A background write failed; re-raised on the training thread at the
    next save/flush/close so errors are never silently swallowed."""


@dataclasses.dataclass
class ResumeState:
    """Where a restored model stopped. ``fit`` consumes this marker: it
    runs epochs ``epoch .. num_epochs-1`` and skips the first
    ``batch_in_epoch`` batches of the resumed epoch."""
    step: int
    epoch: int
    batch_in_epoch: int
    path: str


class CheckpointManager:
    """See module docstring. Typical use::

        cm = CheckpointManager("ckpts", save_every_n_steps=100, keep_last=3)
        net.fit(data, num_epochs=10, checkpoint_manager=cm)
        ...                                  # preemption / crash
        cm = CheckpointManager("ckpts")      # fresh process
        net = cm.restore_latest()            # falls back past torn files
        net.fit(data, num_epochs=10, checkpoint_manager=cm)  # exact resume

    or, restart-proof end to end (checkpoint/resume.py turns the crash +
    restore + refit loop into one call)::

        train_until(net, data, num_epochs=10, checkpoint_manager=cm)

    ``storage`` accepts any checkpoint/storage.py backend — e.g.
    ``CheckpointManager(storage=RetryingBackend(ObjectStoreBackend(bucket)))``
    lands checkpoints in an object store and rides out transient faults;
    ``directory`` alone keeps the historical local-filesystem behavior.
    """

    def __init__(self, directory: Optional[str] = None,
                 save_every_n_steps: Optional[int] = None,
                 save_every_secs: Optional[float] = None,
                 keep_last: Optional[int] = None,
                 keep_best: Optional[str] = None,
                 async_write: bool = True,
                 queue_depth: int = 2,
                 barrier_timeout_s: float = 300.0,
                 save_updater: bool = True,
                 storage=None,
                 sharded: bool = False):
        if save_every_n_steps is not None and save_every_n_steps < 1:
            raise ValueError("save_every_n_steps must be >= 1")
        if keep_best not in (None, "min", "max"):
            raise ValueError("keep_best must be None, 'min' or 'max'")
        if keep_last is not None and keep_last < 1:
            raise ValueError("keep_last must be >= 1")
        if directory is None and storage is None:
            raise ValueError("need a directory or a storage backend")
        self.directory = None if directory is None else str(directory)
        self.save_every_n_steps = save_every_n_steps
        self.save_every_secs = save_every_secs
        self.keep_last = keep_last
        self.keep_best = keep_best
        self.async_write = bool(async_write)
        self.barrier_timeout_s = float(barrier_timeout_s)
        self.save_updater = bool(save_updater)
        # sharded=True: every host writes its own shard (per-host shard
        # files + one set entry in the journal); sharded saves are always
        # synchronous — they end in a cross-host barrier anyway, and the
        # elastic layer only saves at epoch boundaries
        self.sharded = bool(sharded)
        # optional fencing hook run immediately before a journal commit;
        # raising aborts the commit (payloads stay orphaned, never
        # journaled). The elastic layer points this at its membership
        # generation check so a stale, evicted leader cannot journal a
        # checkpoint behind the live generation's back.
        self.commit_guard: Optional[callable] = None
        from deeplearning4j_tpu.checkpoint import manifest as mf
        from deeplearning4j_tpu.checkpoint.storage import LocalFSBackend
        self._mf = mf
        # ``storage`` (checkpoint/storage.py StorageBackend) decouples the
        # journal + payloads from the local filesystem: LocalFSBackend is
        # the historical default, ObjectStoreBackend lands checkpoints in a
        # GCS-style store, RetryingBackend rides transient faults out
        self._storage = (storage if storage is not None
                         else LocalFSBackend(self.directory))
        if self.directory is not None and storage is None:
            os.makedirs(self.directory, exist_ok=True)
        self._storage.clean_orphans()  # partial writes from a crash
        self._lock = threading.Lock()          # guards _entries + manifest
        try:
            entries = mf.load_manifest(self._storage)
        except mf.ManifestError as e:
            log.warning("%s — rebuilding from storage scan", e)
            entries = None
        if entries is None:
            from deeplearning4j_tpu.checkpoint import sharded as shd
            rebuilt_sharded = shd.scan_shard_sets(self._storage)
            if mf.scan_checkpoint_files(self._storage) or rebuilt_sharded:
                # torn OR missing manifest over surviving checkpoint
                # files: rebuild the journal — sha recomputed AND the
                # per-entry metadata (step/metric/...) read back out of
                # each zip, so restore_best / retention / checkpoints()
                # keep working after the rebuild, not just
                # restore_latest. Complete shard SETS rebuild as sharded
                # entries; incomplete sets (crash between shard puts and
                # the journal write) are skipped like tmp/ orphans.
                entries = []
                for e_ in mf.scan_checkpoint_files(self._storage):
                    rebuilt = self._entry_from_object(e_["file"])
                    if rebuilt is not None:
                        entries.append(rebuilt)
                entries.extend(rebuilt_sharded)
                entries.sort(key=lambda e: (int(e.get("step", 0)),
                                            int(e.get("seq", 0))))
                mf.write_manifest(self._storage, entries)
        self._entries: List[dict] = entries or []
        self._seq = max((int(e.get("seq", 0)) for e in self._entries),
                        default=0)
        self._batch_in_epoch = 0
        self._last_save_t = time.monotonic()
        # step-trigger watermark: resumes the cadence from the last
        # committed checkpoint when re-opening an existing directory
        self._last_save_step = (int(self._entries[-1].get("step", 0))
                                if self._entries else 0)
        self._q: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        self._queue_depth = max(1, int(queue_depth))
        self._write_err: Optional[BaseException] = None
        self._fenced_model = None
        self.saves_requested = 0
        self.saves_fenced = 0
        self.saves_committed = 0
        # obs: commit/restore durations + bytes land in the process-wide
        # registry (the counters above are absorbed at scrape time)
        from deeplearning4j_tpu.obs.registry import (
            absorb_checkpoint_manager, get_registry)
        reg = get_registry()
        self._m_commit_ms = reg.histogram(
            "checkpoint_commit_ms", unit="ms",
            help="wall time of one checkpoint payload write + journal "
                 "commit (storage put + manifest)")
        self._m_bytes_written = reg.counter(
            "checkpoint_bytes_written_total", unit="bytes",
            help="checkpoint payload bytes committed to storage")
        self._m_restore_ms = reg.histogram(
            "checkpoint_restore_ms", unit="ms",
            help="wall time of one checkpoint restore (fetch + verify + "
                 "deserialize)")
        self._m_bytes_restored = reg.counter(
            "checkpoint_bytes_restored_total", unit="bytes",
            help="checkpoint payload bytes read back during restores")
        absorb_checkpoint_manager(reg, self)

    def _entry_from_object(self, filename: str) -> Optional[dict]:
        """Reconstruct a full journal entry from a checkpoint zip's own
        metadata (manifest-rebuild path); None if the object is unreadable."""
        import hashlib as _hashlib
        import io
        import json
        import zipfile
        try:
            data = self._storage.get(filename)
            sha = _hashlib.sha256(data).hexdigest()
            with zipfile.ZipFile(io.BytesIO(data), "r") as z:
                meta = json.loads(z.read("metadata.json"))
            if meta.get("shard"):
                return None  # a per-host shard, not a whole checkpoint —
                # scan_shard_sets rebuilds these as one set entry
            return {
                "file": filename,
                "seq": int(meta.get("seq", 0)),
                "step": int(meta.get("iteration", 0)),
                "epoch": int(meta.get("epoch", 0)),
                "batch_in_epoch": int(meta.get("batch_in_epoch", 0)),
                "metric": meta.get("metric"),
                "wall_time": meta.get("wall_time"),
                "sha256": sha,
                "size": len(data),
            }
        except Exception as e:
            log.warning("skipping unreadable checkpoint %s during manifest "
                        "rebuild (%s: %s)", filename, type(e).__name__, e)
            return None

    # --------------------------------------------------------------- triggers
    def _secs_trigger_due(self) -> bool:
        """The wall-clock trigger — SINGLE-HOST ONLY: it reads the local
        monotonic clock, which drifts across hosts, and a save on one host
        but not its peers desyncs the barrier count and times out the
        fleet. Multi-host jobs must use save_every_n_steps (driven by the
        identical iteration counter everywhere)."""
        import jax
        if jax.process_count() > 1:
            raise ValueError(
                "save_every_secs is single-host only: local clocks drift "
                "across processes, so the time trigger would fire on some "
                "hosts and not others and desync the checkpoint barrier — "
                "use save_every_n_steps for multi-host jobs")
        return (time.monotonic() - self._last_save_t) >= self.save_every_secs

    def step_end(self, model, batch_in_epoch: Optional[int] = None):
        """Called by ``fit`` after every optimizer step (``model.iteration``
        already incremented). ``batch_in_epoch`` is the number of batches
        consumed so far in the CURRENT epoch — what exact-step resume skips."""
        if self._fenced_model is not None and model is not self._fenced_model:
            return  # stale thread: must not touch triggers or resume state
        if batch_in_epoch is not None:
            self._batch_in_epoch = int(batch_in_epoch)
        n = self.save_every_n_steps
        # threshold, not exact modulo: tbptt batches advance iteration by
        # SEVERAL windows per step_end, so `iteration % n == 0` would fire
        # only at lcm(windows, n) — or never — instead of every ~n steps
        due = bool(n) and (model.iteration - self._last_save_step) >= n
        if not due and self.save_every_secs is not None:
            due = self._secs_trigger_due()
        if due:
            self.save(model)

    def epoch_end(self, model):
        """Epoch boundary: resume state resets to batch 0 of the (already
        incremented) next epoch; the time trigger may still fire."""
        if self._fenced_model is not None and model is not self._fenced_model:
            return  # stale thread: must not touch triggers or resume state
        self._batch_in_epoch = 0
        if self.save_every_secs is not None and self._secs_trigger_due():
            self.save(model)

    # ------------------------------------------------------------------ fence
    def fence(self, model):
        """Accept saves only from ``model`` from now on (``None`` lifts the
        fence). The auto-resume driver re-fences to each restored model:
        an ABANDONED fit thread (a watchdog-timed-out attempt that cannot
        be cancelled, only outlived) may wake later and try to checkpoint
        its stale lineage through this same manager — the fence drops
        those commits instead of letting them become ``restore_latest``'s
        newest entry behind the recovered run's back."""
        self._fenced_model = model

    # ------------------------------------------------------------------- save
    def save(self, model, metric: Optional[float] = None,
             wait: bool = False) -> Optional[str]:
        """Snapshot ``model`` and commit it (async by default). Returns the
        checkpoint filename on the writer process, ``None`` on non-writers
        and on fenced-out models (see :meth:`fence`).
        ``metric`` (lower/higher better per ``keep_best``) feeds best-model
        retention and ``restore_best``."""
        import jax
        if self._fenced_model is not None and model is not self._fenced_model:
            self.saves_fenced += 1
            log.warning(
                "dropping checkpoint save from a fenced-out model (stale "
                "lineage — an abandoned fit thread?); the manager is "
                "fenced to a different model object")
            return None
        self._raise_pending()
        # reset BOTH trigger watermarks on EVERY process (a non-writer
        # whose watermarks never advanced would re-trigger each step and
        # desync the barrier count across hosts). Note the secs trigger
        # reads local clocks — multi-host jobs should prefer
        # save_every_n_steps, which is driven by the identical iteration
        # counter on every host.
        self._last_save_t = time.monotonic()
        self._last_save_step = int(model.iteration)
        if self.sharded:
            return self._save_sharded(model, metric)
        multi = jax.process_count() > 1
        if multi and jax.process_index() != 0:
            # non-writers only barrier: keeps every host's save points in
            # lockstep so process 0's device_get sync can't skew the step
            # cadence across the fleet
            self._barrier("checkpoint save")
            return None
        from deeplearning4j_tpu.utils.serialization import snapshot_training_state
        snap = snapshot_training_state(model)
        if not self.save_updater:
            snap["opt_state"] = None
        self._seq += 1
        extra = {
            "seq": self._seq,
            "batch_in_epoch": self._batch_in_epoch,
            "wall_time": time.time(),
            "metric": None if metric is None else float(metric),
        }
        filename = f"ckpt-{snap['iteration']:010d}-{self._seq:05d}.zip"
        self.saves_requested += 1
        if self.async_write:
            self._ensure_worker()
            self._q.put((snap, extra, filename))  # bounded: backpressure,
            # a slow disk can't accumulate unbounded host snapshots
        else:
            self._write_and_commit(snap, extra, filename)
        if multi:
            self._barrier("checkpoint save")
        if wait:
            self.flush()
        return filename

    # ------------------------------------------------------ saver protocol
    # (duck-typed EarlyStoppingConfiguration.model_saver backend)
    def save_best_model(self, model, score):
        if self.keep_best is None and self.keep_last is not None:
            # saver contract: get_best_model must return the BEST model,
            # so the best checkpoint must be pinned outside the keep_last
            # window (early stopping minimizes its score → "min")
            log.info("CheckpointManager used as early-stopping saver with "
                     "keep_last but no keep_best — defaulting keep_best="
                     "'min' so retention cannot prune the best checkpoint")
            self.keep_best = "min"
        self.save(model, metric=score)

    def save_latest_model(self, model, score):
        self.save(model, metric=score)

    def get_best_model(self, template=None):
        return self.restore_best()

    # ------------------------------------------------------------ worker side
    def _ensure_worker(self):
        if self._worker is not None and self._worker.is_alive():
            return
        self._q = queue.Queue(maxsize=self._queue_depth)
        self._worker = threading.Thread(target=self._worker_loop,
                                        name="checkpoint-writer", daemon=True)
        self._worker.start()

    _SENTINEL = object()

    def _worker_loop(self):
        while True:
            item = self._q.get()
            try:
                if item is CheckpointManager._SENTINEL:
                    return
                snap, extra, filename = item
                try:
                    self._write_and_commit(snap, extra, filename)
                except BaseException as e:  # surfaced on the training thread
                    log.exception("checkpoint write failed for %s", filename)
                    self._write_err = e
            finally:
                self._q.task_done()

    # ---------------------------------------------------------- sharded save
    def _save_sharded(self, model, metric: Optional[float]) -> Optional[str]:
        """Every host writes its OWN shard; the set becomes one journal
        entry (per-shard sha256) committed by process 0 only after a
        barrier proves every shard durable — the commit of the SET is
        atomic: a crash anywhere before the journal write leaves orphaned
        shards the restore walk never sees. Always synchronous (the save
        ends in a cross-host barrier regardless, and the elastic layer
        saves at epoch boundaries, not on the step cadence)."""
        import jax
        from deeplearning4j_tpu.checkpoint import sharded as shd
        pi, pc = jax.process_index(), jax.process_count()
        t0 = time.perf_counter()
        self._seq += 1  # every host: shard names must agree fleet-wide
        snap = shd.shard_snapshot(model)
        if not self.save_updater:
            snap["updaterState"] = None
        extra = {
            "seq": self._seq,
            "batch_in_epoch": self._batch_in_epoch,
            "wall_time": time.time(),
            "metric": None if metric is None else float(metric),
        }
        base = f"ckpt-{snap['iteration']:010d}-{self._seq:05d}"
        shard_name = shd.shard_object_name(base, pi, pc)
        self.saves_requested += 1
        shard_bytes = shd.shard_zip_bytes(snap, extra)
        self._storage.put(shard_name, shard_bytes)
        self._m_bytes_written.inc(len(shard_bytes))
        self._barrier("sharded payloads durable")
        if pi == 0:
            shards = []
            for host in range(pc):
                name = shd.shard_object_name(base, host, pc)
                data = self._storage.get(name)  # read-back doubles as a
                shards.append({  # read-your-writes durability probe
                    "file": name,
                    "sha256": hashlib.sha256(data).hexdigest(),
                    "size": len(data),
                    # journaled block coverage: lets a selective restore
                    # (sharded.fetch_blocks — the streaming reshard path)
                    # decide which shard OBJECTS it needs without
                    # fetching any payload
                    "blocks": shd.shard_block_summary(data),
                })
            entry = {
                "file": f"{base}.sharded",
                "sharded": True,
                "num_hosts": pc,
                "shards": shards,
                "seq": extra["seq"],
                "step": snap["iteration"],
                "epoch": snap["epoch"],
                "batch_in_epoch": extra["batch_in_epoch"],
                "metric": extra["metric"],
                "wall_time": extra["wall_time"],
                "sha256": None,
                "size": sum(s["size"] for s in shards),
            }
            try:
                if self.commit_guard is not None:
                    self.commit_guard()  # raising aborts the commit
                with self._lock:
                    self._entries.append(entry)
                    self._entries = self._apply_retention(self._entries)
                    self._mf.write_manifest(self._storage, self._entries)
            except BaseException:
                # the un-journaled shard set must not survive: it is a
                # COMPLETE set, and a later manifest-loss rebuild
                # (scan_shard_sets) would resurrect the very checkpoint
                # the fence refused to commit
                for s in shards:
                    try:
                        self._storage.delete(s["file"])
                    except Exception as de:
                        log.warning("could not delete aborted shard %s "
                                    "(%s: %s)", s["file"],
                                    type(de).__name__, de)
                raise
            self.saves_committed += 1
        self._barrier("sharded journal")
        self._m_commit_ms.observe((time.perf_counter() - t0) * 1000.0)
        return f"{base}.sharded" if pi == 0 else None

    def _write_and_commit(self, snap: dict, extra: dict, filename: str):
        from deeplearning4j_tpu.obs.trace import get_tracer
        from deeplearning4j_tpu.utils.serialization import checkpoint_zip_bytes
        t0 = time.perf_counter()
        data = checkpoint_zip_bytes(snap, extra)
        sha = hashlib.sha256(data).hexdigest()
        # fsync_directory deferred to the manifest write below (same dir):
        # the journal entry can never become durable before the payload
        # (a local-fs hint; object-store puts are durable on return)
        self._storage.put(filename, data, fsync_directory=False)
        entry = {
            "file": filename,
            "seq": extra["seq"],
            "step": snap["iteration"],
            "epoch": snap["epoch"],
            "batch_in_epoch": extra["batch_in_epoch"],
            "metric": extra["metric"],
            "wall_time": extra["wall_time"],
            "sha256": sha,
            "size": len(data),
        }
        try:
            if self.commit_guard is not None:
                self.commit_guard()  # raising aborts the journal commit
            with self._lock:
                self._entries.append(entry)
                self._entries = self._apply_retention(self._entries)
                self._mf.write_manifest(self._storage, self._entries)
        except BaseException:
            # the un-journaled payload must not survive a guard abort: a
            # later manifest-loss scan would resurrect the very
            # checkpoint the generation fence refused to commit
            try:
                self._storage.delete(filename)
            except Exception as de:
                log.warning("could not delete aborted checkpoint %s "
                            "(%s: %s)", filename, type(de).__name__, de)
            raise
        self.saves_committed += 1
        commit_ms = (time.perf_counter() - t0) * 1000.0
        self._m_commit_ms.observe(commit_ms)
        self._m_bytes_written.inc(len(data))
        get_tracer().event("checkpoint.commit", file=filename,
                           step=snap.get("iteration"), bytes=len(data),
                           ms=round(commit_ms, 2))

    def _best_entry(self, entries: List[dict],
                    direction: Optional[str] = None) -> Optional[dict]:
        direction = direction or self.keep_best or "min"
        scored = [e for e in entries if e.get("metric") is not None]
        if not scored:
            return None
        key = (lambda e: e["metric"])
        return (min if direction == "min" else max)(scored, key=key)

    def _apply_retention(self, entries: List[dict]) -> List[dict]:
        if self.keep_last is None or len(entries) <= self.keep_last:
            return entries
        keep = set(id(e) for e in entries[-self.keep_last:])
        if self.keep_best:
            best = self._best_entry(entries)
            if best is not None:
                keep.add(id(best))
        kept, pruned = [], []
        for e in entries:
            (kept if id(e) in keep else pruned).append(e)
        from deeplearning4j_tpu.checkpoint.storage import StorageError
        for e in pruned:
            # a sharded entry's payload is its shard SET; the entry's own
            # "file" is a virtual name with no object behind it
            names = ([s["file"] for s in e["shards"]] if e.get("sharded")
                     else [e["file"]])
            for name in names:
                try:
                    self._storage.delete(name)
                except (OSError, StorageError) as err:
                    # retention is best-effort; the manifest is truth
                    log.warning("retention could not delete %s (%s: %s)",
                                name, type(err).__name__, err)
        return kept

    # ---------------------------------------------------------------- control
    def _raise_pending(self):
        err, self._write_err = self._write_err, None
        if err is not None:
            raise CheckpointError("background checkpoint write failed") from err

    def flush(self):
        """Block until every queued snapshot is committed; surface any
        background write error here."""
        if self._q is not None:
            self._q.join()
        self._raise_pending()

    def close(self, wait: bool = True):
        """Drain (when ``wait``) and stop the writer thread. With
        ``wait=False`` nothing here may block: if the writer is wedged on
        hung I/O with a full queue, the sentinel is simply dropped and the
        daemon thread dies with the process."""
        if self._worker is not None and self._worker.is_alive():
            if wait:
                self._q.join()
            try:
                self._q.put_nowait(CheckpointManager._SENTINEL)
            except queue.Full:
                pass  # wedged writer; see docstring
            self._worker.join(timeout=30 if wait else 1)
        self._worker = None
        self._raise_pending()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        # a crash mid-run must not hang on a drain of stale snapshots
        self.close(wait=exc_type is None)
        return False

    def checkpoints(self) -> List[dict]:
        """Committed entries, oldest first (copies)."""
        with self._lock:
            return [dict(e) for e in self._entries]

    def refresh(self) -> int:
        """Re-read the journal from storage and adopt it — for READ-side
        managers watching a store another process writes to (the serving
        hot-swap poller). Returns the number of entries now known. A torn
        or unreadable manifest keeps the previously-known entries (a
        reader must never go blind because it raced the writer's atomic
        manifest replace)."""
        from deeplearning4j_tpu.checkpoint.storage import StorageError
        try:
            entries = self._mf.load_manifest(self._storage)
            self.last_refresh_error = None
        except (self._mf.ManifestError, StorageError, OSError) as e:
            log.warning("manifest refresh failed (%s: %s) — keeping the "
                        "previously loaded journal", type(e).__name__, e)
            entries = None
            # stashed, not raised: this reader stays serviceable on the
            # known journal, but pollers (serving hot-swap) need to SEE
            # the store erroring so they can back their cadence off
            self.last_refresh_error = e
        with self._lock:
            if entries is not None:
                self._entries = entries
                self._seq = max((int(e.get("seq", 0)) for e in self._entries),
                                default=self._seq)
            return len(self._entries)

    def latest_step(self) -> Optional[int]:
        """Step of the newest committed checkpoint, ``None`` when empty —
        the cheap "is there something newer?" probe hot-swap polls."""
        with self._lock:
            if not self._entries:
                return None
            return int(self._entries[-1].get("step", 0))

    # ---------------------------------------------------------------- restore
    def _restorable_entries(self) -> List[dict]:
        with self._lock:
            if self._entries:
                return [dict(e) for e in self._entries]
        return self._mf.scan_checkpoint_files(self._storage)

    def _try_restore(self, entry: dict, load_updater: bool,
                     arm_resume: bool):
        import io
        t0 = time.perf_counter()
        if entry.get("sharded"):
            # shard-set entry: fetch + sha-verify every shard, reassemble
            # the full state (works on ANY restoring world size — the N→M
            # reshard-on-restore path). Any failure raises and the walk
            # falls back a whole generation; shard sets never mix.
            from deeplearning4j_tpu.checkpoint import sharded as shd
            model, meta = shd.restore_sharded(self._storage, entry,
                                              load_updater=load_updater)
        else:
            data = self._storage.get(entry["file"])  # StorageNotFoundError
            if entry.get("sha256") is not None and \
                    hashlib.sha256(data).hexdigest() != entry["sha256"]:
                raise CheckpointError(
                    f"checksum mismatch for {entry['file']} "
                    f"(torn/corrupt write)")
            from deeplearning4j_tpu.utils.serialization import (
                restore_checkpoint)
            model, meta = restore_checkpoint(io.BytesIO(data),
                                             load_updater=load_updater)
        path = (os.path.join(self.directory, entry["file"])
                if self.directory is not None
                else f"{self._storage.describe()}/{entry['file']}")
        info = ResumeState(
            step=int(meta.get("iteration", 0)),
            epoch=int(meta.get("epoch", 0)),
            batch_in_epoch=int(meta.get("batch_in_epoch", 0)),
            path=path)
        # informational provenance, never consumed by fit
        model._restored_from = info
        # the consumable marker is armed ONLY on the crash-resume path
        # (restore_latest): a best-model restore is model SELECTION, and
        # arming it there would make the user's next fine-tune fit()
        # silently reinterpret num_epochs / skip unrelated batches
        model._resume_state = info if arm_resume else None
        self._m_restore_ms.observe((time.perf_counter() - t0) * 1000.0)
        self._m_bytes_restored.inc(int(entry.get("size", 0) or 0))
        return model

    def restore_latest(self, load_updater: bool = True):
        """Newest restorable checkpoint as a fresh model (``None`` when the
        directory holds none). Walks the journal newest-first; a missing
        file, sha mismatch or zip CRC failure logs and falls back to the
        previous complete checkpoint. The returned model carries a
        :class:`ResumeState` consumed by its next ``fit``."""
        if self._worker is not None and self._worker.is_alive():
            self.flush()
        for entry in reversed(self._restorable_entries()):
            try:
                return self._try_restore(entry, load_updater, arm_resume=True)
            except Exception as e:
                log.warning("checkpoint %s unusable (%s: %s); falling back",
                            entry.get("file"), type(e).__name__, e)
        return None

    def restore_best(self, direction: Optional[str] = None,
                     load_updater: bool = True):
        """Best-``metric`` restorable checkpoint (direction defaults to
        ``keep_best`` or "min"); falls back to next-best on corruption.
        Model selection, not crash resume: the returned model carries its
        provenance in ``_restored_from`` but NO consumable resume marker —
        a subsequent ``fit`` trains normally."""
        if self._worker is not None and self._worker.is_alive():
            self.flush()
        entries = [e for e in self._restorable_entries()
                   if e.get("metric") is not None]
        direction = direction or self.keep_best or "min"
        entries.sort(key=lambda e: e["metric"],
                     reverse=(direction == "max"))
        for entry in entries:
            try:
                return self._try_restore(entry, load_updater,
                                         arm_resume=False)
            except Exception as e:
                log.warning("checkpoint %s unusable (%s: %s); falling back",
                            entry.get("file"), type(e).__name__, e)
        return None

    def restore_blocks(self, want, filename: Optional[str] = None,
                       trees=("coefficients", "updaterState")):
        """Streaming reshard-on-restore: fetch only the blocks ``want``
        selects from a SHARDED checkpoint (the newest one, or the named
        journal entry), without reassembling the full state — see
        ``checkpoint.sharded.fetch_blocks``. Per-host bytes read scale
        with the host's share of the state instead of its whole size."""
        from deeplearning4j_tpu.checkpoint import sharded as shd
        if self._worker is not None and self._worker.is_alive():
            self.flush()
        entries = [e for e in self._restorable_entries() if e.get("sharded")]
        if filename is not None:
            entries = [e for e in entries if e.get("file") == filename]
        if not entries:
            raise CheckpointError(
                "no sharded checkpoint entry"
                + (f" named {filename!r}" if filename else "")
                + " to fetch blocks from")
        return shd.fetch_blocks(self._storage, entries[-1], want,
                                trees=tuple(trees))

    def restore_entry(self, filename: str, load_updater: bool = True):
        """Restore one SPECIFIC committed checkpoint by its journal
        ``file`` name (sharded set entries use their virtual
        ``*.sharded`` name). Model selection like ``restore_best`` — no
        resume marker is armed. Raises :class:`CheckpointError` when the
        journal has no such entry; integrity failures propagate (no
        fallback — the caller asked for exactly this checkpoint)."""
        if self._worker is not None and self._worker.is_alive():
            self.flush()
        for entry in self._restorable_entries():
            if entry.get("file") == filename:
                return self._try_restore(entry, load_updater,
                                         arm_resume=False)
        raise CheckpointError(f"no journal entry named {filename!r}")

    # ------------------------------------------------------------- multi-host
    def _barrier(self, what: str):
        """Bounded collective barrier (watchdog deadline pattern): a dead
        peer at a checkpoint point raises CollectiveTimeoutError with
        process/device diagnostics instead of hanging the fleet."""
        import jax
        if jax.process_count() <= 1:
            return
        from deeplearning4j_tpu.parallel.watchdog import CollectiveWatchdog
        from jax.experimental import multihost_utils
        CollectiveWatchdog(timeout_s=self.barrier_timeout_s).call(
            lambda: multihost_utils.sync_global_devices(f"checkpoint:{what}"),
            what=f"checkpoint barrier ({what})")


def consume_resume_state(model):
    """Pop the model's resume marker (set by ``restore_latest``); returns a
    :class:`ResumeState` or ``None``. Shared by every ``fit`` wire-in."""
    rs = getattr(model, "_resume_state", None)
    model._resume_state = None
    return rs


def resume_plan(model, num_epochs: int):
    """Consume the model's resume marker and return ``(epochs_to_run,
    skip_batches)`` for a fit targeting ``num_epochs`` TOTAL epochs. The
    single definition of the resume arithmetic — every fit wire-in
    (MultiLayerNetwork, ComputationGraph, ParallelWrapper, ClusterTrainer)
    calls this instead of re-deriving it."""
    rs = consume_resume_state(model)
    if rs is None:
        return num_epochs, 0
    epochs_to_run = max(0, num_epochs - model.epoch)
    if epochs_to_run == 0:
        # legitimate when the checkpointed run had already reached the
        # target, but silent no-op training would be baffling otherwise —
        # say what happened and how to get plain semantics
        log.warning(
            "fit() on a restored model trains 0 epochs: num_epochs=%d is "
            "the run's TOTAL target and the checkpoint is already at epoch "
            "%d. To fine-tune a restored model with plain num_epochs "
            "semantics, clear the marker first (model._resume_state = "
            "None) or restore via restore_best().", num_epochs, model.epoch)
    return epochs_to_run, int(rs.batch_in_epoch)


_EXHAUSTED = object()


def skip_consumed_batches(data, skip: int):
    """One epoch pass over ``data`` minus its first ``skip`` batches,
    WITHOUT materializing the skipped ones — callers place this UNDER any
    prefetch/placement wrapper so already-consumed batches are never
    staged, padded or transferred just to be discarded. (Bucket-padding
    wrappers stay ABOVE the skip: pad targets must evolve exactly as in
    the uninterrupted run.)

    Raises when the stream ends before ``skip`` batches: the resume
    contract requires replaying the interrupted run's data in the same
    order, and an exhausted one-shot generator or shorter dataset would
    otherwise silently train a no-op epoch and diverge from the
    bitwise-resume guarantee.

    SEEKABLE sources (``iter_from(start_batch)`` — datasets/sharded.py's
    ShardedReader, incl. wrapped in AsyncDataSetIterator) skip by
    seeking: the consumed batches are never fetched, sliced or ledgered
    at all, which is what makes resume fleet-true — a restoring worker
    at ANY world size jumps straight to the checkpoint's
    ``batch_in_epoch`` cursor instead of replaying its way there."""
    if skip and hasattr(data, "iter_from"):
        return iter(data.iter_from(skip))
    it = iter(data)
    for i in range(skip):
        if next(it, _EXHAUSTED) is _EXHAUSTED:
            raise ValueError(
                f"exact-step resume expected to skip {skip} already-"
                f"consumed batches, but the data stream ended after {i} — "
                "resume requires a re-iterable source replaying the "
                "interrupted run's batches in the same order")
    return it
