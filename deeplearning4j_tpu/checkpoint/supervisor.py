"""Process-level preemption recovery: ``train_until_process``.

``train_until`` (checkpoint/resume.py) restarts a crashed fit WITHIN the
same process — which cannot help when the crash corrupts in-process state
(a wedged XLA runtime, a poisoned allocator, a SIGKILL). This module is
the scheduler-shaped half: a supervisor that runs each fit attempt as a
fresh OS process (the ``tests/multihost_worker.py`` harness shape) and
respawns on failure, so recovery survives anything short of losing the
checkpoint store. ``RestartPolicy`` / ``CrashRecord`` / ``RunSummary``
semantics carry over from ``train_until`` — the budget, jittered backoff
and crash history read identically; only the unit of restart changed from
"fit attempt" to "worker process".

Exit-code protocol (what a worker process tells the supervisor):

- ``0``              — this worker's training target is complete;
- ``ELASTIC_RESTART_EXIT`` (17) — in-process elastic recovery failed
  (``ElasticRestartRequired``): respawn me, I will rejoin the next
  membership generation;
- killed by a signal — preemption: respawned only with
  ``respawn_preempted=True`` (an elastic fleet keeps training WITHOUT the
  preempted worker; a fixed-world job wants it back);
- any other code   — a crash: respawn under the restart budget.

A worker that neither exits nor progresses is bounded by
``attempt_timeout_s`` (killed and treated as a crash) and the whole run
by ``overall_timeout_s`` — a supervised fleet can never hang its caller,
which is also what lets the chaos tests carry hard suite timeouts.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import signal
import subprocess
import tempfile
import time
from typing import Callable, Dict, List, Optional, Sequence, Union

from deeplearning4j_tpu.checkpoint.resume import (
    CrashRecord, RestartBudgetExceeded, RestartPolicy, RunSummary)
from deeplearning4j_tpu.utils.backoff import backoff_delay

log = logging.getLogger(__name__)

#: exit code a worker uses to say "respawn me" (parallel/elastic.py raises
#: ElasticRestartRequired; the worker script maps it to this code)
ELASTIC_RESTART_EXIT = 17

__all__ = ["train_until_process", "ProcessRunSummary", "ProcessCrashRecord",
           "ELASTIC_RESTART_EXIT"]


@dataclasses.dataclass
class ProcessCrashRecord(CrashRecord):
    """A ``CrashRecord`` that also names the worker process it belongs
    to (``train_until``'s records carry over 1:1 otherwise)."""
    worker: int = 0


@dataclasses.dataclass
class ProcessRunSummary(RunSummary):
    """``RunSummary`` plus per-worker outcomes and log paths. ``model``
    is always None at the process level — the result of a supervised run
    lives in the checkpoint store, not in the supervisor's memory."""
    worker_status: Dict[int, str] = dataclasses.field(default_factory=dict)
    logs: Dict[int, List[str]] = dataclasses.field(default_factory=dict)


class _Worker:
    """Supervisor-side state for one worker slot."""

    def __init__(self, index: int):
        self.index = index
        self.proc: Optional[subprocess.Popen] = None
        self.log_path: Optional[str] = None
        self.logs: List[str] = []
        self.started_at = 0.0
        self.attempt = 0           # spawn count for THIS slot
        self.status = "pending"    # running | completed | down | pending
        self.respawn_at: Optional[float] = None  # backoff gate


def train_until_process(worker_argv: Union[Sequence[str], Callable],
                        num_workers: int = 1,
                        restart_policy: Optional[RestartPolicy] = None,
                        checkpoint_manager=None,
                        respawn_preempted: bool = False,
                        attempt_timeout_s: Optional[float] = None,
                        overall_timeout_s: Optional[float] = None,
                        poll_s: float = 0.1,
                        env: Optional[dict] = None,
                        cwd: Optional[str] = None,
                        log_dir: Optional[str] = None,
                        on_restart: Optional[Callable] = None
                        ) -> ProcessRunSummary:
    """Run ``num_workers`` worker processes to completion, respawning per
    the exit-code protocol above under ``restart_policy``'s budget.

    ``worker_argv`` is the argv list every worker runs, or a callable
    ``(worker_index, attempt) -> argv`` (attempt is 1-based per slot).
    Workers learn their identity from their argv — the supervisor passes
    nothing implicitly.

    ``checkpoint_manager`` (optional, read-only here) annotates the crash
    history with the store's latest committed step at each crash/respawn
    (``refresh()`` + ``latest_step()``) — the operator sees how much
    progress each crash cost, exactly like ``train_until``'s records.

    ``on_restart(worker_index, attempt)`` fires before each respawn.

    Returns a :class:`ProcessRunSummary` once every worker has either
    completed or gone permanently down, with at least one completion.
    Raises :class:`RestartBudgetExceeded` (carrying the summary) when the
    restart budget runs out, when every worker is down with none
    complete, or when ``overall_timeout_s`` expires (everything is killed
    first — the caller never inherits a zombie fleet).
    """
    policy = restart_policy if restart_policy is not None else RestartPolicy()
    rng = random.Random(policy.seed)
    if log_dir is None:
        log_dir = tempfile.mkdtemp(prefix="train_until_process_")
    os.makedirs(log_dir, exist_ok=True)
    workers = [_Worker(i) for i in range(num_workers)]
    crashes: List[ProcessCrashRecord] = []
    restarts = 0
    t0 = time.monotonic()
    t0_wall = time.time()

    def argv_for(w: _Worker) -> List[str]:
        if callable(worker_argv):
            return list(worker_argv(w.index, w.attempt))
        return list(worker_argv)

    def store_step() -> Optional[int]:
        if checkpoint_manager is None:
            return None
        try:
            checkpoint_manager.refresh()
            return checkpoint_manager.latest_step()
        except Exception as e:
            log.warning("could not read store progress (%s: %s)",
                        type(e).__name__, e)
            return None

    def spawn(w: _Worker):
        w.attempt += 1
        w.log_path = os.path.join(log_dir,
                                  f"worker{w.index}-a{w.attempt}.log")
        w.logs.append(w.log_path)
        out = open(w.log_path, "wb")  # the file object is handed to the
        try:                          # child; closing ours is safe
            w.proc = subprocess.Popen(argv_for(w), stdout=out,
                                      stderr=subprocess.STDOUT,
                                      env=env, cwd=cwd)
        finally:
            out.close()
        w.started_at = time.monotonic()
        w.status = "running"
        w.respawn_at = None
        log.info("worker %d attempt %d spawned (pid %d)", w.index,
                 w.attempt, w.proc.pid)

    def kill_all():
        for w in workers:
            if w.proc is not None and w.proc.poll() is None:
                try:
                    w.proc.kill()
                    w.proc.wait(timeout=10)
                except (OSError, subprocess.TimeoutExpired) as e:
                    log.warning("could not kill worker %d (%s: %s)",
                                w.index, type(e).__name__, e)

    def summary(completed: bool) -> ProcessRunSummary:
        return ProcessRunSummary(
            model=None, completed=completed, restarts=restarts,
            crashes=list(crashes), wall_time_s=time.monotonic() - t0,
            worker_status={w.index: w.status for w in workers},
            logs={w.index: list(w.logs) for w in workers})

    def give_up(message: str):
        kill_all()
        s = summary(False)
        log.error("train_until_process giving up: %s — %s", message, s)
        raise RestartBudgetExceeded(message, s)

    attached_dumps: set = set()

    def flight_tail() -> Optional[List[str]]:
        """The victim's last seconds, read back ACROSS the process
        boundary: the worker's crash flight recorder (obs/flight.py)
        flushed its ring into the checkpoint store before dying. Dumps
        predating this run are ignored, and each dump is attached to at
        most ONE crash record, oldest-unattached first — crashes are
        recorded in observation order, so two workers dying in the same
        monitor window each get their own victim's dump instead of both
        showing the newest one (best-effort: the supervisor cannot map
        its slot index to the worker's self-chosen recorder id).
        Watchdog-timeout dumps sort LAST: the elastic membership-bump
        escalation can flush one from a worker that then keeps running,
        so a dump flushed by an actual death always wins and a watchdog
        dump is attached only when nothing else is fresh (the non-elastic
        path, where the timeout did kill the attempt)."""
        if checkpoint_manager is None:
            return None
        store = getattr(checkpoint_manager, "_storage", None)
        if store is None:
            return None
        try:
            from deeplearning4j_tpu.obs.flight import (dump_tail_summary,
                                                       read_dumps)
            fresh = [d for d in read_dumps(store)
                     if d.get("time", 0.0) >= t0_wall
                     and (d.get("worker_id"), d.get("time"))
                     not in attached_dumps]
            if fresh:
                fresh.sort(key=lambda d: (
                    str(d.get("reason", "")).startswith("watchdog timeout"),
                    d.get("time", 0.0)))
                dump = fresh[0]  # oldest unattached non-diagnostic first
                attached_dumps.add((dump.get("worker_id"),
                                    dump.get("time")))
                return dump_tail_summary(dump)
        except Exception as e:
            log.warning("could not read flight dump (%s: %s)",
                        type(e).__name__, e)
        return None

    def record(w: _Worker, kind: str, detail: str, backoff: float):
        crashes.append(ProcessCrashRecord(
            attempt=len(crashes) + 1, error_type=kind, error=detail,
            crashed_at_step=store_step(), restored_step=None,
            restored_epoch=None, backoff_s=backoff, worker=w.index,
            flight_tail=flight_tail()))

    def schedule_respawn(w: _Worker, kind: str, detail: str):
        nonlocal restarts
        restarts += 1
        if restarts > policy.max_restarts:
            record(w, kind, detail, 0.0)
            give_up(f"restart budget exhausted after {policy.max_restarts} "
                    f"restarts (last: worker {w.index} {kind}: {detail})")
        delay = (backoff_delay(restarts - 1, base_s=policy.backoff_s,
                               cap_s=policy.max_backoff_s, rng=rng)
                 if policy.backoff_s > 0 else 0.0)
        record(w, kind, detail, delay)
        w.status = "pending"
        w.respawn_at = time.monotonic() + delay
        log.warning("worker %d %s (%s) — respawn %d/%d in %.2fs", w.index,
                    kind, detail, restarts, policy.max_restarts, delay)
        if on_restart is not None:
            on_restart(w.index, w.attempt + 1)

    try:
        return _supervise(workers, spawn, kill_all, summary, give_up,
                          record, schedule_respawn, store_step, crashes,
                          policy, respawn_preempted, attempt_timeout_s,
                          overall_timeout_s, poll_s, t0)
    except RestartBudgetExceeded:
        raise  # give_up already killed the fleet
    except BaseException:
        # an unexpected failure (bad argv from a callable, exec OSError,
        # KeyboardInterrupt) must not leak live workers to the caller
        kill_all()
        raise


def _supervise(workers, spawn, kill_all, summary, give_up, record,
               schedule_respawn, store_step, crashes, policy,
               respawn_preempted, attempt_timeout_s, overall_timeout_s,
               poll_s, t0):
    for w in workers:
        spawn(w)
    while True:
        now = time.monotonic()
        if overall_timeout_s is not None and now - t0 > overall_timeout_s:
            give_up(f"overall deadline of {overall_timeout_s:.0f}s expired "
                    "with workers still running")
        for w in workers:
            if w.status == "pending" and w.respawn_at is not None \
                    and now >= w.respawn_at:
                # annotate THIS worker's latest crash record with where
                # the store stands — the step this attempt restores to
                # (crashes[-1] may belong to a different worker)
                for rec_ in reversed(crashes):
                    if rec_.worker == w.index:
                        rec_.restored_step = store_step()
                        break
                spawn(w)
            if w.status != "running":
                continue
            rc = w.proc.poll()
            if rc is None:
                if attempt_timeout_s is not None and \
                        now - w.started_at > attempt_timeout_s:
                    try:
                        w.proc.kill()
                        w.proc.wait(timeout=10)
                    except (OSError, subprocess.TimeoutExpired) as e:
                        log.warning("hung worker %d unkillable (%s: %s)",
                                    w.index, type(e).__name__, e)
                    schedule_respawn(
                        w, "AttemptTimeout",
                        f"no exit within {attempt_timeout_s:.0f}s")
                continue
            if rc == 0:
                w.status = "completed"
                log.info("worker %d completed (attempt %d)", w.index,
                         w.attempt)
            elif rc == ELASTIC_RESTART_EXIT:
                schedule_respawn(w, "ElasticRestartRequired",
                                 "worker asked to be respawned "
                                 f"(exit {rc})")
            elif rc < 0:
                signame = signal.Signals(-rc).name if -rc in \
                    signal.Signals._value2member_map_ else str(-rc)
                preemption = -rc in (signal.SIGKILL, signal.SIGTERM)
                if not preemption:
                    # SIGABRT/SIGSEGV etc. are crashes (a poisoned
                    # runtime aborting), not the scheduler taking the
                    # machine — respawn under the budget
                    schedule_respawn(w, "ProcessCrash",
                                     f"killed by {signame}")
                elif respawn_preempted:
                    schedule_respawn(w, "Preempted",
                                     f"killed by {signame}")
                else:
                    w.status = "down"
                    record(w, "Preempted",
                           f"killed by {signame}; not respawned "
                           "(respawn_preempted=False)", 0.0)
                    log.warning("worker %d preempted (%s) — continuing "
                                "with survivors", w.index, signame)
            else:
                schedule_respawn(w, "ProcessCrash", f"exit code {rc}")
        statuses = {w.status for w in workers}
        if "running" not in statuses and "pending" not in statuses:
            if "completed" in statuses:
                s = summary(True)
                log.info("%s", s)
                return s
            give_up("every worker is permanently down and none completed")
        time.sleep(poll_s)
