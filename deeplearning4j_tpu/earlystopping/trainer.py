"""Early-stopping trainer.

Parity surface: reference earlystopping/EarlyStoppingConfiguration.java,
trainer/BaseEarlyStoppingTrainer.java: per-epoch score on a validation set,
best-model tracking via a saver, iteration + epoch termination conditions.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import List, Optional

from deeplearning4j_tpu.earlystopping.conditions import (
    EpochTerminationCondition, IterationTerminationCondition,
)
from deeplearning4j_tpu.earlystopping.savers import InMemoryModelSaver

log = logging.getLogger(__name__)


@dataclasses.dataclass
class EarlyStoppingConfiguration:
    epoch_termination_conditions: List[EpochTerminationCondition]
    iteration_termination_conditions: List[IterationTerminationCondition] = \
        dataclasses.field(default_factory=list)
    model_saver: object = dataclasses.field(default_factory=InMemoryModelSaver)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


@dataclasses.dataclass
class EarlyStoppingResult:
    termination_reason: str  # "epoch_condition" | "iteration_condition" | "error"
    termination_details: str
    total_epochs: int
    best_model_epoch: int
    best_model_score: float
    score_vs_epoch: dict
    best_model: object


class EarlyStoppingTrainer:
    """Drive fit() epoch-by-epoch with validation scoring (see module doc).

    ``score_calculator``: callable(model) -> float; defaults to loss on the
    validation iterator (reference scorecalc/DataSetLossCalculator.java).

    ``checkpoint_manager`` (checkpoint.CheckpointManager) plugs the
    checkpoint/ subsystem in as the saver backend: best/latest models
    become durable, checksummed, retention-bounded checkpoints (the
    manager implements the saver protocol — save_best_model /
    save_latest_model / get_best_model via restore_best). Passing one
    overrides ``config.model_saver``. The manager's ``storage=`` backend
    carries through unchanged — a manager built over
    ``RetryingBackend(ObjectStoreBackend(...))`` gives early stopping
    object-store durability with transient-fault retries for free, and
    ``get_best_model`` inherits restore_best's corruption fallback
    (next-best checkpoint, never garbage).
    """

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_data, validation_data=None, score_calculator=None,
                 checkpoint_manager=None):
        if checkpoint_manager is not None:
            config = dataclasses.replace(config,
                                         model_saver=checkpoint_manager)
        self.config = config
        self.model = model
        self.train_data = train_data
        self.validation_data = validation_data
        if score_calculator is None:
            if validation_data is None:
                raise ValueError("Need validation_data or a score_calculator")

            def score_calculator(m):
                from deeplearning4j_tpu.datasets.dataset import DataSet
                total, n = 0.0, 0
                for ds in self.validation_data:
                    total += m.score_dataset(ds) * ds.num_examples()
                    n += ds.num_examples()
                return total / max(n, 1)
        self.score_calculator = score_calculator

    def _fit_batch(self, ds) -> bool:
        """One training batch; returns whether the batch actually trained.
        Overridden by the parallel trainer to route through a
        ParallelWrapper (which may drop ragged batches)."""
        self.model.fit(ds)
        return True

    def fit(self) -> EarlyStoppingResult:
        for c in (self.config.epoch_termination_conditions
                  + self.config.iteration_termination_conditions):
            c.initialize()
        best_score = math.inf
        best_epoch = -1
        score_vs_epoch = {}
        last_computed_score = math.inf
        epoch = 0
        reason, details = "epoch_condition", ""
        while True:
            # --- one epoch of training with iteration-condition checks ---
            stop_iter = None
            trained_any = False
            for ds in self.train_data:
                if self._fit_batch(ds) is not False:
                    trained_any = True
                s = self.model.score()
                for cond in self.config.iteration_termination_conditions:
                    if cond.terminate(s):
                        stop_iter = cond
                        break
                if stop_iter is not None:
                    break
            if not trained_any:
                raise ValueError(
                    "No training batch was usable this epoch (empty data, or "
                    "every batch dropped as ragged by the parallel wrapper)")
            if stop_iter is not None:
                reason = "iteration_condition"
                details = type(stop_iter).__name__
                break
            # --- validation scoring (every N epochs) ---
            score = None
            if epoch % self.config.evaluate_every_n_epochs == 0:
                score = float(self.score_calculator(self.model))
                score_vs_epoch[epoch] = score
                if score < best_score:
                    best_score = score
                    best_epoch = epoch
                    self.config.model_saver.save_best_model(self.model, score)
                if self.config.save_last_model:
                    self.config.model_saver.save_latest_model(self.model, score)
            # --- epoch termination (reference BaseEarlyStoppingTrainer):
            # score-free conditions every epoch; score-based conditions only
            # on epochs where a validation score was actually computed, so
            # patience counters tick per evaluation, not per epoch ---
            if score is not None:
                last_computed_score = score
            stop_epoch = None
            for cond in self.config.epoch_termination_conditions:
                if getattr(cond, "requires_score", True) and score is None:
                    continue
                if cond.terminate(epoch, last_computed_score):
                    stop_epoch = cond
                    break
            if stop_epoch is not None:
                details = type(stop_epoch).__name__
                epoch += 1
                break
            epoch += 1
        best_model = self.config.model_saver.get_best_model(self.model)
        if best_model is None and best_epoch >= 0:
            # a best model WAS saved but the saver cannot hand it back
            # (every checkpoint torn/corrupt, or the store lost them):
            # degrade to the live in-memory model rather than returning
            # None for a run that demonstrably trained — loudly, because
            # the live model is the LAST state, not the best-scoring one
            log.warning(
                "model saver could not return the saved best model (epoch "
                "%d, score %.6g) — storage fault or pruned checkpoint; "
                "falling back to the live final-state model", best_epoch,
                best_score)
            best_model = self.model
        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            total_epochs=epoch,
            best_model_epoch=best_epoch,
            best_model_score=best_score,
            score_vs_epoch=score_vs_epoch,
            best_model=best_model,
        )
