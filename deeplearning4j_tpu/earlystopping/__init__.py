from deeplearning4j_tpu.earlystopping.trainer import (  # noqa: F401
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    EarlyStoppingResult,
)
from deeplearning4j_tpu.earlystopping.conditions import (  # noqa: F401
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
    InvalidScoreIterationTerminationCondition,
)
from deeplearning4j_tpu.earlystopping.savers import (  # noqa: F401
    InMemoryModelSaver,
    LocalFileModelSaver,
)
