"""Model savers for early stopping.

Parity surface: reference earlystopping/saver/{InMemoryModelSaver,
LocalFileModelSaver,LocalFileGraphSaver}.java.

Snapshots are copied to HOST memory (``jax.device_get``): the train steps use
``donate_argnums``, so aliasing the live device buffers would leave the saved
"best model" pointing at deleted arrays after the next fit() step.
"""

from __future__ import annotations

import os

import jax


def _clone_model(template):
    """Fresh model object of the template's class/conf (params overwritten by
    the caller) — get_best_model must not mutate the live training model."""
    model = type(template)(template.conf)
    model.init()
    return model


class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._latest = None

    @staticmethod
    def _snapshot(model):
        # device_get: host copies, immune to later buffer donation
        return (jax.device_get(model.params), jax.device_get(model.state))

    def save_best_model(self, model, score):
        self._best = (self._snapshot(model), score)

    def save_latest_model(self, model, score):
        self._latest = (self._snapshot(model), score)

    def get_best_model(self, template):
        if self._best is None:
            return None
        (params, state), _ = self._best
        model = _clone_model(template)
        model.params, model.state = params, state
        return model


class LocalFileModelSaver:
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name):
        return os.path.join(self.directory, name)

    def save_best_model(self, model, score):
        from deeplearning4j_tpu.utils.serialization import write_model
        write_model(model, self._path("bestModel.zip"))

    def save_latest_model(self, model, score):
        from deeplearning4j_tpu.utils.serialization import write_model
        write_model(model, self._path("latestModel.zip"))

    def get_best_model(self, template=None):
        from deeplearning4j_tpu.utils.serialization import restore
        path = self._path("bestModel.zip")
        if not os.path.exists(path):
            return None
        return restore(path)
