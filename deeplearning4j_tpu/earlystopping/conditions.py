"""Early-stopping termination conditions.

Parity surface: reference earlystopping/termination/ (6 conditions):
MaxEpochsTerminationCondition, MaxScoreIterationTerminationCondition,
MaxTimeIterationTerminationCondition, ScoreImprovementEpochTerminationCondition,
InvalidScoreIterationTerminationCondition (doubles as NaN/divergence guard),
BestScoreEpochTerminationCondition.
"""

from __future__ import annotations

import math
import time


class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, score: float) -> bool:
        raise NotImplementedError


class EpochTerminationCondition:
    # Conditions that read the validation score are only checked on epochs
    # where one was computed (evaluate_every_n_epochs); score-free conditions
    # (max epochs) are checked every epoch.
    requires_score = True

    def initialize(self):
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    requires_score = False

    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score is at least as good as a target."""

    def __init__(self, best_expected: float):
        self.best_expected = best_expected

    def terminate(self, epoch, score):
        return score <= self.best_expected


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs with no (sufficient) improvement."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0):
        self.max_no_improve = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self.best = math.inf
        self.since = 0

    def initialize(self):
        self.best = math.inf
        self.since = 0

    def terminate(self, epoch, score):
        if self.best - score > self.min_improvement:
            self.best = score
            self.since = 0
            return False
        self.since += 1
        return self.since > self.max_no_improve


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Stop immediately if the score explodes past a bound."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, score):
        return score > self.max_score


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    """NaN/Inf guard (reference InvalidScoreIterationTerminationCondition.java;
    the reference's divergence-detection story — SURVEY §5)."""

    def terminate(self, score):
        return math.isnan(score) or math.isinf(score)


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start = None

    def initialize(self):
        self._start = time.monotonic()

    def terminate(self, score):
        if self._start is None:
            self._start = time.monotonic()
        return time.monotonic() - self._start > self.max_seconds
