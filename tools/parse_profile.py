"""Parse the captured xplane and print top self-time TPU ops, aggregated by
HLO op name. PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python python tools/parse_profile.py [xplane.pb]
"""
import collections
import glob
import os
import sys

from tensorflow.tsl.profiler.protobuf import xplane_pb2


def main():
    if len(sys.argv) > 1:
        xp = sys.argv[1]
    else:
        xp = sorted(glob.glob(os.path.join(
            os.path.dirname(__file__), "profile_out",
            "**", "*.xplane.pb"), recursive=True))[-1]
    space = xplane_pb2.XSpace()
    with open(xp, "rb") as f:
        space.ParseFromString(f.read())
    print("planes:", [(p.name, len(p.lines)) for p in space.planes])
    for plane in space.planes:
        if "TPU" not in plane.name and "tpu" not in plane.name.lower():
            continue
        emeta = plane.event_metadata
        for line in plane.lines:
            # XLA op lines carry per-HLO timing
            agg = collections.defaultdict(float)
            cnt = collections.Counter()
            total = 0.0
            for ev in line.events:
                name = emeta[ev.metadata_id].name
                dur = ev.duration_ps / 1e12
                agg[name] += dur
                cnt[name] += 1
                total += dur
            if total == 0:
                continue
            rows = sorted(agg.items(), key=lambda kv: -kv[1])
            print(f"\n== plane '{plane.name}' line '{line.name}' "
                  f"total {total*1e3:.1f} ms over {sum(cnt.values())} events")
            for name, t in rows[:25]:
                print(f"  {t*1e3:8.2f} ms  x{cnt[name]:<4d} {name[:110]}")


if __name__ == "__main__":
    main()
