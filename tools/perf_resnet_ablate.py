"""Ablate ResNet50 train-step costs: BN variants, precision, stem conv.

Monkeypatches pieces of the stack one at a time and re-times the full train
step (honest value-fetch sync). PYTHONPATH=. python tools/perf_resnet_ablate.py
"""
import dataclasses as dc
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.nn.conf.normalization import BatchNormalization
from deeplearning4j_tpu.nn.graph import ComputationGraph

SIDE = 224
BATCH = 128
PEAK = 197e12


def _fwd_flops(net):
    import bench
    return bench._model_fwd_flops_per_image(net)
ORIG_APPLY = BatchNormalization.apply


def bn_apply_bf16(self, params, state, x, *, train=False, rng=None, mask=None):
    """BN with stats in compute dtype (no f32 upcast)."""
    axes = tuple(range(x.ndim - 1))
    if train:
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        new_state = {
            "mean": self.decay * state["mean"] + (1.0 - self.decay) * mean.astype(jnp.float32),
            "var": self.decay * state["var"] + (1.0 - self.decay) * var.astype(jnp.float32),
        }
    else:
        mean, var = state["mean"].astype(x.dtype), state["var"].astype(x.dtype)
        new_state = state
    mean = mean.astype(x.dtype)
    var = var.astype(x.dtype)
    xhat = (x - mean) * lax.rsqrt(var + jnp.asarray(self.eps, x.dtype))
    out = params["gamma"] * xhat + params["beta"]
    return out, new_state


def bn_apply_frozen(self, params, state, x, *, train=False, rng=None, mask=None):
    """BN as a pure scale+shift (no batch stats at all) — cost upper bound."""
    scale = params["gamma"] * lax.rsqrt(state["var"] + self.eps)
    shift = params["beta"] - state["mean"] * scale
    return x * scale.astype(x.dtype) + shift.astype(x.dtype), state


def time_step(tag):
    conf = dc.replace(
        ResNet50(num_classes=1000, input_shape=(SIDE, SIDE, 3)).conf(),
        dtype="bfloat16")
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((BATCH, SIDE, SIDE, 3), np.float32))
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, BATCH)])
    step = net._get_jitted("train")
    loss = [None]

    def run_one():
        net._rng, k = jax.random.split(net._rng)
        net.params, net.state, net.opt_state, loss[0] = step(
            net.params, net.state, net.opt_state, k, [x], [y], None, None)
    for _ in range(3):
        run_one()
    float(loss[0])
    t0 = time.perf_counter()
    for _ in range(20):
        run_one()
    float(loss[0])
    dt = (time.perf_counter() - t0) / 20
    print(f"{tag:24s}: step {dt*1e3:7.1f} ms | imgs/s {BATCH/dt:8.1f} "
          f"| mfu {BATCH*3*_fwd_flops(net)/dt/PEAK:.3f}", flush=True)


def main():
    from deeplearning4j_tpu.nn.conf.convolutional import SubsamplingLayer
    orig_pool = SubsamplingLayer.apply

    def pool_as_avg(self, params, state, x, *, train=False, rng=None, mask=None):
        self = dc.replace(self, pooling_type="avg")
        return orig_pool(self, params, state, x, train=train, rng=rng, mask=mask)

    time_step("baseline(f32-stats)")
    BatchNormalization.apply = bn_apply_frozen
    time_step("bn-frozen")
    SubsamplingLayer.apply = pool_as_avg
    time_step("bn-frozen+avgpool")
    BatchNormalization.apply = ORIG_APPLY
    time_step("avgpool-only")
    SubsamplingLayer.apply = orig_pool


if __name__ == "__main__":
    main()
