#!/usr/bin/env python
"""Run the framework linter (analysis/lint.py) over the repo.

Usage:
    python tools/run_lint.py [path ...]

With no arguments lints the tier-1 surface: ``deeplearning4j_tpu/``,
``bench.py`` and ``tools/``. Exits 1 on any violation — the same contract
``tests/test_lint.py`` enforces in CI. Rules DLT001-DLT007 (import-time
jnp, impure-in-jit, unsynced bench stopwatches, lock-order, unfolded
serving BN, swallowed checkpoint/storage errors, metrics registered
without units/help) are documented in ``analysis/lint.py``. Waive a
finding inline with
``# lint: disable=DLT00X`` (or file-wide with ``# lint: disable-file=...``)
and a short justification.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.analysis.lint import DEFAULT_TARGETS, lint_paths  # noqa: E402


def main(argv) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    targets = argv[1:] or DEFAULT_TARGETS(repo_root)
    violations = lint_paths(targets)
    for v in violations:
        print(v)
    n = len(violations)
    print(f"lint: {n} violation{'s' if n != 1 else ''} in "
          f"{len(targets)} target(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
