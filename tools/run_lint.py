#!/usr/bin/env python
"""Run the framework linter (analysis/lint.py) over the repo.

Usage:
    python tools/run_lint.py [options] [path ...]

With no paths lints the tier-1 surface: ``deeplearning4j_tpu/``,
``bench.py`` and ``tools/``. Exits 1 on any violation (or, with
``--audit-waivers``, on any stale waiver) — the same contract
``tests/test_lint.py`` enforces in CI.

Options:
    --json            machine-readable output: one object with
                      ``violations`` (rule/file/line/message, plus
                      ``chain`` — the resolved call chain — for
                      interprocedural findings) and, with
                      ``--audit-waivers``, ``stale_waivers``.
    --rule DLT0XX     only report the named rule(s); repeatable, and a
                      comma-separated list works too. Filters REPORTING
                      only — the whole-repo call graph is still built so
                      interprocedural rules stay sound.
    --changed-only    only report findings in files changed vs git HEAD
                      (staged, unstaged, or untracked) for fast local
                      runs. The graph is still built over the full
                      targets, so a changed caller is checked against
                      unchanged callees and vice versa.
    --audit-waivers   additionally flag ``# lint: disable=...`` comments
                      that no longer suppress any finding (stale waivers
                      hide the next real regression).

Per-file rules DLT001-016 and the interprocedural families DLT017-019
(host-work-reachable-from-jit, cross-module lock analysis, thread
lifecycle) are documented in ``analysis/lint.py``; the README carries the
full rule table. Waive a finding inline with ``# lint: disable=DLT00X``
(or file-wide with ``# lint: disable-file=...``) and a short
justification.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.analysis.lint import (  # noqa: E402
    DEFAULT_TARGETS, audit_waivers, lint_paths)

_CHAIN_RE = re.compile(r"via ([^(]+?) \(\d+ call hop")
_RULE_RE = re.compile(r"^DLT\d{3}$")


def _chain_of(message: str):
    """The resolved call chain embedded in a DLT017 message, or None."""
    m = _CHAIN_RE.search(message)
    if not m:
        return None
    return [part.strip() for part in m.group(1).split("->")]


def _changed_files(repo_root: str):
    """Absolute paths changed vs HEAD (staged+unstaged) plus untracked."""
    out = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(args, cwd=repo_root, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line:
                out.add(os.path.abspath(os.path.join(repo_root, line)))
    return out


def main(argv) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    as_json = False
    changed_only = False
    audit = False
    rules = set()
    targets = []
    args = list(argv[1:])
    while args:
        a = args.pop(0)
        if a == "--json":
            as_json = True
        elif a == "--changed-only":
            changed_only = True
        elif a == "--audit-waivers":
            audit = True
        elif a == "--rule":
            if not args:
                print("--rule needs an argument (e.g. --rule DLT017)",
                      file=sys.stderr)
                return 2
            rules.update(r.strip() for r in args.pop(0).split(",") if r)
        elif a.startswith("--rule="):
            rules.update(r.strip() for r in a.split("=", 1)[1].split(",")
                         if r)
        elif a.startswith("-"):
            print(f"unknown option: {a}", file=sys.stderr)
            return 2
        else:
            targets.append(a)
    for r in rules:
        if not _RULE_RE.match(r):
            print(f"--rule expects DLT0XX ids, got: {r}", file=sys.stderr)
            return 2

    targets = targets or DEFAULT_TARGETS(repo_root)
    violations = lint_paths(targets)
    stale = audit_waivers(targets) if audit else []

    if changed_only:
        changed = _changed_files(repo_root)
        if changed is None:
            print("--changed-only: git unavailable, reporting everything",
                  file=sys.stderr)
        else:
            violations = [v for v in violations
                          if os.path.abspath(v.file) in changed]
            stale = [s for s in stale if os.path.abspath(s.file) in changed]
    if rules:
        violations = [v for v in violations if v.rule in rules]

    if as_json:
        payload = {
            "violations": [
                {"rule": v.rule, "file": v.file, "line": v.line,
                 "message": v.message, "chain": _chain_of(v.message)}
                for v in violations],
            "count": len(violations),
        }
        if audit:
            payload["stale_waivers"] = [
                {"file": s.file, "line": s.line, "rules": list(s.rules),
                 "scope": s.scope} for s in stale]
        print(json.dumps(payload, indent=2))
    else:
        for v in violations:
            print(v)
        for s in stale:
            print(s)
        n = len(violations)
        print(f"lint: {n} violation{'s' if n != 1 else ''} in "
              f"{len(targets)} target(s)"
              + (f", {len(stale)} stale waiver(s)" if audit else ""))
    return 1 if violations or stale else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
