"""Data-lake object-store CLI (checkpoint/cloud.py): poke any backend the
runtime can talk to — cloud bucket, local directory, in-process test
double — through the one shared URL syntax.

    # list a bucket (credentials from DLT_LAKE_* / AWS_* env or --access-key)
    python tools/lake.py ls http://127.0.0.1:9000/lake --prefix shards/

    # fetch / upload one object (multipart above the client threshold)
    python tools/lake.py get http://127.0.0.1:9000/lake shards/meta.json
    python tools/lake.py put http://127.0.0.1:9000/lake model.zip --in model.zip

    # reap stale tmp-* keys and abandoned multipart uploads
    python tools/lake.py gc http://127.0.0.1:9000/lake

    # disk-cache layer: point any command at a cache dir; cache-stats
    # reports its hit rate / byte budget
    python tools/lake.py get ... --cache-dir /var/cache/lake
    python tools/lake.py cache-stats file:/ckpts --cache-dir /var/cache/lake

URLs: ``http(s)://host:port/bucket`` (CloudObjectBackend behind bounded
retries, Retry-After honored), ``file:/path`` or a bare path
(LocalFSBackend), ``mem:`` (fresh in-process store — only useful for
exercising the CLI itself).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# uploads are staged fully in memory (the client's put contract), so the
# CLI bounds what it will read from a local file
MAX_PUT_BYTES = 1 << 31


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("url", help="backend URL: http(s)://host:port/"
                                    "bucket, file:/path, bare path, mem:")
        sp.add_argument("--cache-dir", default=None,
                        help="wrap the backend in a local-disk LRU cache")
        sp.add_argument("--cache-bytes", type=int, default=256 << 20)
        sp.add_argument("--access-key", default=None,
                        help="override env/file credential resolution")
        sp.add_argument("--secret-key", default=None)
        sp.add_argument("--timeout-s", type=float, default=10.0)
        sp.add_argument("--retries", type=int, default=5)
        return sp

    ls = common(sub.add_parser("ls", help="list object names"))
    ls.add_argument("--prefix", default="", help="name prefix filter")
    ls.add_argument("-l", "--long", action="store_true",
                    help="also fetch and print each object's size")

    get = common(sub.add_parser("get", help="fetch one object"))
    get.add_argument("key")
    get.add_argument("--out", default=None,
                     help="write here instead of stdout")

    put = common(sub.add_parser("put", help="upload one object"))
    put.add_argument("key")
    put.add_argument("--in", dest="infile", required=True,
                     help="local file to upload")

    common(sub.add_parser(
        "gc", help="clean_orphans: delete tmp-*/.part keys and abort "
                   "abandoned multipart uploads"))

    common(sub.add_parser("cache-stats",
                          help="print the --cache-dir tier's counters"))
    return p


def _backend(args):
    from deeplearning4j_tpu.checkpoint.cloud import backend_from_url
    return backend_from_url(
        args.url, cache_dir=args.cache_dir, cache_bytes=args.cache_bytes,
        retries=args.retries, timeout_s=args.timeout_s,
        access_key=args.access_key, secret_key=args.secret_key)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    backend = _backend(args)

    if args.cmd == "ls":
        for name in backend.list(prefix=args.prefix):
            if args.long:
                print(f"{len(backend.get(name)):>12}  {name}")
            else:
                print(name)
        return 0

    if args.cmd == "get":
        data = backend.get(args.key)
        if args.out:
            with open(args.out, "wb") as f:
                f.write(data)
            print(f"{args.key}: {len(data)} bytes -> {args.out}",
                  file=sys.stderr)
        else:
            sys.stdout.buffer.write(data)
        return 0

    if args.cmd == "put":
        with open(args.infile, "rb") as f:
            data = f.read(MAX_PUT_BYTES + 1)
        if len(data) > MAX_PUT_BYTES:
            print(f"{args.infile} exceeds the {MAX_PUT_BYTES}-byte "
                  "single-object bound", file=sys.stderr)
            return 1
        backend.put(args.key, data)
        print(f"{args.key}: {len(data)} bytes uploaded", file=sys.stderr)
        return 0

    if args.cmd == "gc":
        swept = backend.clean_orphans()
        for name in swept or ():
            print(name)
        print(f"swept {len(swept or ())} orphan(s)", file=sys.stderr)
        return 0

    if args.cmd == "cache-stats":
        if not args.cache_dir:
            print("cache-stats needs --cache-dir", file=sys.stderr)
            return 1
        for k, v in sorted(backend.stats().items()):
            print(f"{k}: {v}")
        return 0

    return 2  # unreachable: argparse enforces the subcommand set


if __name__ == "__main__":
    raise SystemExit(main())
