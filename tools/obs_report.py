#!/usr/bin/env python
"""Render an obs JSONL event log (or a flight-recorder dump) as a
human-readable post-mortem report.

Usage:
    python tools/obs_report.py RUN.jsonl [flightrec-w03 ...] [--top N]

Inputs are files written by ``obs.EventLog`` (JSON lines of span/event
records) and/or ``obs.FlightRecorder.flush`` (one JSON object with an
``events`` list) — with a ``LocalFSBackend`` store these are plain files
in the store directory. Multiple inputs merge into one report; records
appearing in several inputs (the crash ring overlaps the event log when
both came from the same process) are counted once.

Sections:
  - **Per-step phase breakdown** — the ``train.*`` spans (data-wait /
    host / device) aggregated: count, total/mean/p50/p95/max ms;
  - **Span summary** — every span name aggregated the same way;
  - **Slowest spans** — the top-N individual spans with their attrs;
  - **Crash-ring tail** — the newest records of each flight dump, with
    its flush reason (what the victim was doing in its last seconds);
  - **Events** — non-span lifecycle breadcrumbs (generation boundaries,
    checkpoint commits, watchdog diagnostics).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_records(path: str) -> Tuple[List[dict], Optional[dict]]:
    """Parse one input file. Returns ``(records, dump)`` — ``dump`` is the
    flight-dump envelope when the file is one (its events are ALSO in
    ``records``), else None. Unparseable lines are skipped (a crashed
    writer may leave a torn tail)."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    stripped = text.lstrip()
    if stripped.startswith("{"):
        try:
            obj = json.loads(stripped)
            if isinstance(obj, dict) and isinstance(obj.get("events"), list):
                return list(obj["events"]), obj
        except ValueError:
            pass
    records = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            records.append(rec)
    return records, None


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def _agg_table(spans: List[dict], title: str) -> List[str]:
    by_name: Dict[str, List[float]] = {}
    for s in spans:
        by_name.setdefault(s.get("name", "?"), []).append(
            float(s.get("dur_ms", 0.0)))
    if not by_name:
        return []
    lines = [title, "-" * len(title),
             f"{'span':<28} {'count':>7} {'total_ms':>10} {'mean_ms':>9} "
             f"{'p50_ms':>8} {'p95_ms':>8} {'max_ms':>9}"]
    for name in sorted(by_name, key=lambda n: -sum(by_name[n])):
        ds = sorted(by_name[name])
        total = sum(ds)
        lines.append(
            f"{name:<28} {len(ds):>7} {total:>10.2f} "
            f"{total / len(ds):>9.3f} {_percentile(ds, 0.50):>8.3f} "
            f"{_percentile(ds, 0.95):>8.3f} {ds[-1]:>9.2f}")
    lines.append("")
    return lines


def _fmt_attrs(attrs: Optional[dict]) -> str:
    if not attrs:
        return ""
    return " ".join(f"{k}={v}" for k, v in attrs.items())


def render_report(records: List[dict], dumps: Optional[List[dict]] = None,
                  top: int = 10) -> str:
    """The report as one string (see module docstring for the sections)."""
    spans = [r for r in records if r.get("kind") == "span"]
    events = [r for r in records if r.get("kind") == "event"]
    lines: List[str] = [
        "observability report",
        "====================",
        f"{len(records)} records ({len(spans)} spans, {len(events)} "
        f"events)", ""]
    phase_spans = [s for s in spans
                   if str(s.get("name", "")).startswith("train.")]
    lines += _agg_table(phase_spans, "Per-step phase breakdown (train.*)")
    lines += _agg_table(spans, "Span summary (all)")
    slowest = sorted(spans, key=lambda s: -float(s.get("dur_ms", 0.0)))[:top]
    if slowest:
        lines += ["Slowest spans", "-------------"]
        for s in slowest:
            lines.append(f"{float(s.get('dur_ms', 0.0)):>10.2f} ms  "
                         f"{s.get('name', '?')}  {_fmt_attrs(s.get('attrs'))}")
        lines.append("")
    for dump in dumps or []:
        # one-liner format shared with CrashRecord.flight_tail — the
        # sys.path insert up top makes the package importable when the
        # script runs standalone from any cwd
        from deeplearning4j_tpu.obs.flight import dump_tail_summary
        head = (f"Crash-ring tail — worker {dump.get('worker_id', '?')} "
                f"(flushed: {dump.get('reason', '?')})")
        lines += [head, "-" * len(head)]
        for line in dump_tail_summary(dump, n=top)[1:]:
            lines.append("  " + line)
        lines.append("")
    if events:
        lines += ["Events", "------"]
        for r in events[-top * 2:]:
            lines.append(f"  {r.get('name', '?')}  "
                         f"{_fmt_attrs(r.get('attrs'))}")
        lines.append("")
    return "\n".join(lines)


def main(argv) -> int:
    top = 10
    paths = []
    args = list(argv[1:])
    while args:
        a = args.pop(0)
        if a == "--top":
            if not args:
                print("--top needs a value", file=sys.stderr)
                return 2
            top = int(args.pop(0))
        else:
            paths.append(a)
    if not paths:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    records: List[dict] = []
    seen = set()
    dumps: List[dict] = []
    for p in paths:
        recs, dump = load_records(p)
        for rec in recs:
            key = json.dumps(rec, sort_keys=True, default=str)
            if key not in seen:
                seen.add(key)
                records.append(rec)
        if dump is not None:
            dumps.append(dump)
    records.sort(key=lambda r: r.get("wall", 0.0))
    print(render_report(records, dumps, top=top))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
