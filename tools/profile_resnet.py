"""ResNet50 MFU ablation harness (VERDICT r3 item 1).

Runs a series of on-device experiments to locate where the 85% idle time
goes: batch-size scaling, dispatch-granularity (scan-of-K inner steps vs
per-batch dispatch), fp32 vs bf16, and XLA cost analysis to validate the
FLOP denominator used by bench.py.

Each timing uses the honest end-of-run loss VALUE fetch (see
tpu-perf-gotchas: block_until_ready alone is unreliable over the tunnel).

Usage: python tools/profile_resnet.py [outfile]
"""

from __future__ import annotations

import dataclasses as dc
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.nn.conf.layers import apply_constraints
from deeplearning4j_tpu.nn.graph import ComputationGraph

SIDE = 224
def _train_flops_per_img(net):
    import bench
    return 3 * bench._model_fwd_flops_per_image(net)  # graph-derived (r4)
PEAK = 197e12  # v5e bf16


def emit(out, **kw):
    line = json.dumps(kw)
    print(line, flush=True)
    out.write(line + "\n")
    out.flush()


def make(batch, dtype="bfloat16"):
    conf = dc.replace(
        ResNet50(num_classes=1000, input_shape=(SIDE, SIDE, 3)).conf(),
        dtype=dtype)
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, SIDE, SIDE, 3), np.float32))
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.integers(0, 1000, batch)])
    return net, x, y


def bench_per_batch(out, batch, dtype="bfloat16", steps=30, warmup=3,
                    cost=False):
    net, x, y = make(batch, dtype)
    step = net._get_jitted("train")
    if cost:
        try:
            c = step.lower(net.params, net.state, net.opt_state, net._rng,
                           [x], [y], None, None).compile().cost_analysis()
            if isinstance(c, (list, tuple)):
                c = c[0]
            emit(out, exp="cost_analysis", batch=batch, dtype=dtype,
                 xla_flops=c.get("flops"),
                 xla_flops_per_img=c.get("flops", 0) / batch,
                 bench_assumed_per_img=_train_flops_per_img(net))
        except Exception as e:
            emit(out, exp="cost_analysis", error=repr(e))
    loss = None

    def one():
        nonlocal loss
        net._rng, k = jax.random.split(net._rng)
        net.params, net.state, net.opt_state, loss = step(
            net.params, net.state, net.opt_state, k, [x], [y], None, None)

    t_c0 = time.perf_counter()
    one()
    float(loss)
    compile_s = time.perf_counter() - t_c0
    for _ in range(warmup):
        one()
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        one()
    float(loss)
    dt = time.perf_counter() - t0
    ips = steps * batch / dt
    emit(out, exp="per_batch", batch=batch, dtype=dtype, steps=steps,
         imgs_per_sec=round(ips, 1), ms_per_step=round(1000 * dt / steps, 2),
         mfu=round(ips * _train_flops_per_img(net) / PEAK, 4),
         compile_s=round(compile_s, 1))
    return ips


def bench_scan(out, batch, K=8, outer=5, dtype="bfloat16"):
    """Same train step, but K steps fused into one dispatch via lax.scan.
    If this beats per-batch dispatch, the gap is dispatch/tunnel overhead,
    not device compute."""
    net, x, y = make(batch, dtype)
    vag = jax.value_and_grad(net._loss_fn, has_aux=True)

    def single(carry, _):
        params, state, opt, rng = carry
        rng, k = jax.random.split(rng)
        (loss, new_state), grads = vag(params, state, [x], [y], k, None, None)
        new_params = dict(params)
        new_opt = dict(opt)
        for n in net._layer_names:
            g = net._gnorms[n](grads[n])
            up, os_ = net._txs[n].update(g, opt[n], params[n])
            new_params[n] = apply_constraints(
                net.vertices[n][0], optax.apply_updates(params[n], up))
            new_opt[n] = os_
        return (new_params, new_state, new_opt, rng), loss

    @jax.jit
    def multi(params, state, opt, rng):
        (p, s, o, r), losses = jax.lax.scan(
            single, (params, state, opt, rng), None, length=K)
        return p, s, o, r, losses[-1]

    carry = (net.params, net.state, net.opt_state, net._rng)
    p, s, o, r, loss = multi(*carry)
    float(loss)
    p, s, o, r, loss = multi(p, s, o, r)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(outer):
        p, s, o, r, loss = multi(p, s, o, r)
    float(loss)
    dt = time.perf_counter() - t0
    ips = outer * K * batch / dt
    emit(out, exp="scan_fused", batch=batch, K=K, dtype=dtype,
         imgs_per_sec=round(ips, 1),
         ms_per_step=round(1000 * dt / (outer * K), 2),
         mfu=round(ips * _train_flops_per_img(net) / PEAK, 4))
    return ips


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/profile_resnet.jsonl"
    out = open(path, "w")
    emit(out, devices=str(jax.devices()))
    # 1. reproduce r3 baseline + XLA flop count
    bench_per_batch(out, 128, cost=True)
    # 2. batch scaling
    for b in (256, 512):
        try:
            bench_per_batch(out, b)
        except Exception as e:
            emit(out, exp="per_batch", batch=b, error=repr(e))
    # 3. dispatch-granularity ablation at batch 128 and 256
    for b in (128, 256):
        try:
            bench_scan(out, b)
        except Exception as e:
            emit(out, exp="scan_fused", batch=b, error=repr(e))
    # 4. fp32 reference point at 128
    bench_per_batch(out, 128, dtype="float32", steps=15)
    emit(out, done=True)


if __name__ == "__main__":
    main()
