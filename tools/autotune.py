"""Offline HBM-planner + compile-time-autotuner CLI (perf/ subsystem).

Load a model (a ``CheckpointManager`` directory, a model zip, or a zoo
model by name), search batch size / fusion / donation — and, with
``--budget``, per-layer remat policies under the stated HBM budget — then
write the winning ``TuningRecord`` as JSON::

    python tools/autotune.py --model zoo:lenet --batch-sizes 8,16,32 \
        --budget 512M --out lenet.tuning.json --report

The record is what the rest of the stack consumes:

- ``perf.autotune.build_network(conf, record)`` / ``apply_tuning`` — a
  fresh training replica inherits the tuned conf + batch size;
- ``ParallelInference(tuning=record)`` / ``ModelServer.add_model(
  tuning=record)`` — a serving endpoint adopts the recorded bucket ladder,
  warmed at registration (zero compiles at serve time);
- models saved with the record attached carry ``tuning.json`` in their
  zip/checkpoints, so restores inherit it automatically.

``--save-into-ckpt`` drops ``tuning.json`` into the checkpoint DIRECTORY
next to the checkpoints (the ``calibration.json`` convention from
tools/quantize.py). Budgets accept ``K``/``M``/``G`` suffixes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


_ZOO = ("lenet", "simplecnn", "alexnet", "vgg16", "resnet50", "darknet19",
        "googlenet")


def _load_conf(spec: str):
    """Configuration for --model: ``zoo:<name>[:<h>x<w>x<c>]``, a
    CheckpointManager directory, or a model zip."""
    if spec.startswith("zoo:"):
        parts = spec.split(":")
        name = parts[1].lower()
        shape = None
        if len(parts) > 2:
            shape = tuple(int(d) for d in parts[2].split("x"))
        from deeplearning4j_tpu import models
        cls = {"lenet": models.LeNet, "simplecnn": models.SimpleCNN,
               "alexnet": models.AlexNet, "vgg16": models.VGG16,
               "resnet50": models.ResNet50, "darknet19": models.Darknet19,
               "googlenet": models.GoogLeNet}.get(name)
        if cls is None:
            raise SystemExit(f"error: unknown zoo model '{name}' "
                             f"(known: {', '.join(_ZOO)})")
        kw = {"num_classes": 10}
        if shape is not None:
            kw["input_shape"] = shape
        try:
            return cls(**kw).conf()
        except TypeError:  # models without input_shape (LeNet)
            return cls(num_classes=10).conf()
    if os.path.isdir(spec):
        from deeplearning4j_tpu.checkpoint import CheckpointManager
        cm = CheckpointManager(spec)
        try:
            net = cm.restore_latest(load_updater=False)
        finally:
            cm.close()
        if net is None:
            raise SystemExit(f"error: no restorable checkpoint in {spec!r}")
        return net.conf
    from deeplearning4j_tpu.utils.serialization import restore
    return restore(spec).conf


def parse_bytes(s: str) -> int:
    s = s.strip().upper()
    mult = 1
    for suffix, m in (("K", 2**10), ("M", 2**20), ("G", 2**30)):
        if s.endswith(suffix):
            s, mult = s[:-1], m
            break
    return int(float(s) * mult)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", required=True,
                   help="zoo:<name>[:<HxWxC>], a CheckpointManager "
                        "directory, or a model zip")
    p.add_argument("--out", required=True, help="TuningRecord JSON path")
    p.add_argument("--batch-sizes", default="8,16,32",
                   help="comma list of candidate training batch sizes")
    p.add_argument("--budget", default=None,
                   help="HBM budget (bytes; K/M/G suffixes) — enables the "
                        "memory planner (per-layer remat search)")
    p.add_argument("--fusion", choices=("auto", "on", "off"), default="auto")
    p.add_argument("--no-donation-search", action="store_true",
                   help="only search donate=True (the default execution)")
    p.add_argument("--top-k", type=int, default=2,
                   help="candidates to wall-clock confirm")
    p.add_argument("--reps", type=int, default=2,
                   help="timed repetitions per confirmed candidate")
    p.add_argument("--max-serving-batch", type=int, default=None,
                   help="top of the recorded serving bucket ladder "
                        "(default: the chosen batch size)")
    p.add_argument("--serving-rows", default=None,
                   help="comma list of observed serving row counts — "
                        "learns the ladder from the histogram instead of "
                        "the pow2 default")
    p.add_argument("--plan-only", action="store_true",
                   help="run the HBM planner only (needs --budget); print "
                        "the plan, write no record")
    p.add_argument("--save-into-ckpt", action="store_true",
                   help="also write tuning.json into the checkpoint "
                        "directory (--model must be a directory)")
    p.add_argument("--report", action="store_true",
                   help="print the full record JSON instead of the "
                        "one-line summary")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from deeplearning4j_tpu.perf.autotune import autotune
    from deeplearning4j_tpu.perf.planner import plan_memory

    conf = _load_conf(args.model)
    budget = None if args.budget is None else parse_bytes(args.budget)
    batch_sizes = tuple(int(b) for b in args.batch_sizes.split(",") if b)
    fusion = {"auto": "auto", "on": True, "off": False}[args.fusion]

    if args.plan_only:
        if budget is None:
            raise SystemExit("error: --plan-only needs --budget")
        plan = plan_memory(conf, budget, minibatch=max(batch_sizes),
                           fusion=fusion)
        print(plan.summary())
        return 0

    serving_rows = (None if args.serving_rows is None else
                    [int(r) for r in args.serving_rows.split(",") if r])
    record = autotune(
        conf, batch_sizes=batch_sizes, fusion=fusion,
        donation=((True,) if args.no_donation_search else (True, False)),
        budget_bytes=budget, top_k=args.top_k, reps=args.reps,
        serving_rows=serving_rows,
        max_serving_batch=args.max_serving_batch)
    record.save(args.out)
    if args.save_into_ckpt:
        if os.path.isdir(args.model):
            record.save(os.path.join(args.model, "tuning.json"))
        else:
            print("warning: --save-into-ckpt needs --model to be a "
                  "checkpoint DIRECTORY; nothing written for "
                  f"{args.model!r}", file=sys.stderr)
    if args.report:
        print(json.dumps(record.to_dict(), indent=2, sort_keys=True))
    else:
        print(json.dumps({
            "out": args.out,
            "batch_size": record.batch_size,
            "fusion": record.fusion,
            "donate": record.donate,
            "remat_layers": len(record.remat),
            "buckets": list(record.buckets),
            "step_seconds": record.objective.get("step_seconds"),
            "candidates": record.candidates_searched,
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
