"""ResNet50 perf decomposition on one real TPU chip.

Times fwd-only, fwd+bwd, and the full train step at several batch sizes so
we can see where the MFU goes. Honest sync: fetch a scalar VALUE derived
from the last step's output (block_until_ready does not force completion
through the axon tunnel). Run: PYTHONPATH=. python tools/perf_resnet.py
"""
import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.nn.graph import ComputationGraph

SIDE = 224
PEAK = 197e12


def _fwd_flops(net):
    import bench
    return bench._model_fwd_flops_per_image(net)


def bench(run_one, fetch, steps=20, warmup=3):
    from deeplearning4j_tpu.obs import Stopwatch
    for _ in range(warmup):
        run_one()
    fetch()
    sw = Stopwatch().start()
    for _ in range(steps):
        run_one()
    fetch()  # the sync: reads a VALUE derived from the last step's output
    return sw.stop() / steps


def main():
    import os
    batches = [int(b) for b in os.environ.get("PERF_BATCHES", "128,256").split(",")]
    for batch in batches:
        conf = dc.replace(
            ResNet50(num_classes=1000, input_shape=(SIDE, SIDE, 3)).conf(),
            dtype="bfloat16")
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((batch, SIDE, SIDE, 3), np.float32))
        y = jnp.asarray(np.eye(1000, dtype=np.float32)[
            rng.integers(0, 1000, batch)])

        # fwd only
        fwd = jax.jit(lambda p, s, xi: net._forward(p, s, [xi], False, None,
                                                    None)[0]["output"])
        out = [None]

        def run_fwd():
            out[0] = fwd(net.params, net.state, x)
        t_f = bench(run_fwd, lambda: float(out[0][0, 0]))

        # fwd + bwd (grad wrt params), reduced to one scalar per leaf chain
        grad_fn = jax.jit(jax.grad(
            lambda p, s, xi, yi: net._loss_fn(p, s, [xi], [yi],
                                              jax.random.key(0), None, None)[0]))

        def run_grad():
            out[0] = grad_fn(net.params, net.state, x, y)
        t_g = bench(run_grad,
                    lambda: float(out[0]["output"]["W"][0, 0]))

        # full train step (donating buffers, like the real bench)
        step = net._get_jitted("train")
        loss = [None]

        def run_step():
            net._rng, k = jax.random.split(net._rng)
            net.params, net.state, net.opt_state, loss[0] = step(
                net.params, net.state, net.opt_state, k, [x], [y], None, None)
        t_s = bench(run_step, lambda: float(loss[0]))

        train_flops = batch * 3 * _fwd_flops(net)
        print(f"batch={batch}: fwd {t_f*1e3:7.1f} ms | grad {t_g*1e3:7.1f} ms "
              f"| step {t_s*1e3:7.1f} ms | imgs/s {batch/t_s:8.1f} "
              f"| mfu {train_flops/t_s/PEAK:.3f}", flush=True)


if __name__ == "__main__":
    main()
