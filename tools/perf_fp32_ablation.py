"""fp32 ResNet50 precision-mode ablation (VERDICT r4 #6).

Times the full train step at batch 128 under each jax matmul-precision mode
so the fp32 row in BENCH and the "use bf16" guidance are backed by numbers:

  default  — TPU lowers f32 convs to bf16xbf16->f32 MXU passes (1 pass)
  float32/highest — bf16_6x-style multi-pass emulation of true f32

Also reports the bf16 compute-dtype step for reference. Honest sync: value
fetch. Run: PYTHONPATH=.:/root/.axon_site python tools/perf_fp32_ablation.py
"""
import dataclasses as dc
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.nn.graph import ComputationGraph

BATCH = 128
PEAK = 197e12


def build(dtype):
    conf = dc.replace(
        ResNet50(num_classes=1000, input_shape=(224, 224, 3)).conf(),
        dtype=dtype)
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((BATCH, 224, 224, 3), np.float32))
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.integers(0, 1000, BATCH)])
    step = net._get_jitted("train")
    return net, step, x, y


def time_step(net, step, x, y, steps=15, warmup=4):
    loss = [None]

    def run_one():
        net._rng, k = jax.random.split(net._rng)
        net.params, net.state, net.opt_state, loss[0] = step(
            net.params, net.state, net.opt_state, k, [x], [y], None, None)

    for _ in range(warmup):
        run_one()
    float(loss[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        run_one()
    float(loss[0])
    return (time.perf_counter() - t0) / steps


def main():
    import bench
    fwd_flops = None
    rows = []
    for dtype, prec in [("float32", "default"), ("float32", "float32"),
                        ("bfloat16", "default")]:
        with jax.default_matmul_precision(prec):
            net, step, x, y = build(dtype)
            if fwd_flops is None:
                fwd_flops = bench._model_fwd_flops_per_image(net)
            dt = time_step(net, step, x, y)
        imgs = BATCH / dt
        tflops = 3 * fwd_flops * imgs / 1e12
        rows.append((dtype, prec, dt * 1e3, imgs, tflops, tflops * 1e12 / PEAK))
        print(f"{dtype:9s} precision={prec:8s}: {dt*1e3:6.1f} ms/step "
              f"{imgs:7.1f} imgs/s  {tflops:6.1f} TF/s  mfu {tflops*1e12/PEAK:.3f}",
              flush=True)
    return rows


if __name__ == "__main__":
    main()
