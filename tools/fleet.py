"""Run a local serving fleet (fleet/ CLI): router + N checkpoint-serving
replicas over a shared lease store.

    # one command: router on :9200, 2 replicas, shared store directory
    python tools/fleet.py up --replicas 2 --model iris=/ckpts/iris \
        --store /tmp/fleet --router-port 9200

    curl -s localhost:9200/readyz
    curl -s -X POST localhost:9200/v1/models/iris:predict \
        -d '{"inputs": [[5.1, 3.5, 1.4, 0.2]]}'
    curl -s localhost:9200/v1/fleet          # topology: leases + placement

Replicas restore each model's latest checkpoint — the ``TuningRecord``
riding the checkpoint warms the exact serving ladder before the lease
flips ready, so a fresh replica serves its first request with zero
steady-state compiles. SIGTERM anywhere drains: replicas withdraw their
lease FIRST (the router stops routing immediately), then finish every
admitted request; the router stops accepting after its replicas exit.

Subcommands ``router`` and ``replica`` run a single process each (what
``up`` spawns; also the chaos tests' SIGKILL targets). ``smoke`` runs an
in-process end-to-end check with no checkpoints needed.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_model(spec: str):
    name, sep, path = spec.partition("=")
    if not sep or not name or not path:
        raise argparse.ArgumentTypeError(
            f"--model takes name=checkpoint_dir; got {spec!r}")
    return name, path


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp, models_required=True):
        sp.add_argument("--store", required=True,
                        help="shared lease/membership store directory")
        if models_required:
            sp.add_argument("--model", action="append", type=_parse_model,
                            required=True, metavar="NAME=CKPT_DIR")
        sp.add_argument("--ttl-s", type=float, default=5.0,
                        help="replica lease TTL")
        sp.add_argument("--drain-timeout-s", type=float, default=30.0)

    up = sub.add_parser("up", help="router + N replica subprocesses")
    common(up)
    up.add_argument("--replicas", type=int, default=2)
    up.add_argument("--router-port", type=int, default=9200)
    up.add_argument("--bind", default="127.0.0.1")
    up.add_argument("--poll-secs", type=float, default=None,
                    help="checkpoint hot-swap poll cadence per replica")

    rep = sub.add_parser("replica", help="one replica process")
    common(rep)
    rep.add_argument("--replica-id", default=None)
    rep.add_argument("--port", type=int, default=0)
    rep.add_argument("--bind", default="127.0.0.1")
    rep.add_argument("--poll-secs", type=float, default=None)

    rt = sub.add_parser("router", help="one router process")
    common(rt, models_required=False)
    rt.add_argument("--port", type=int, default=9200)
    rt.add_argument("--bind", default="127.0.0.1")

    sub.add_parser("smoke", help="in-process end-to-end fleet check")
    return p


def _wait_for_signal(on_signal=None) -> threading.Event:
    done = threading.Event()

    def _handler(signum, frame):
        if on_signal:
            on_signal(signum)
        done.set()
    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)
    return done


# ------------------------------------------------------------------ replica
def cmd_replica(args) -> int:
    from deeplearning4j_tpu.fleet.replica import restore_and_serve
    replica = restore_and_serve(
        args.store, list(args.model), replica_id=args.replica_id,
        port=args.port, bind_address=args.bind, poll_secs=args.poll_secs,
        ttl_s=args.ttl_s, wait_ready_s=0)
    done = _wait_for_signal(
        lambda s: print(f"replica {replica.replica_id}: signal {s}, "
                        f"draining ({replica.server.inflight} in flight)",
                        flush=True))
    replica.wait_ready(300.0)
    print(f"replica {replica.replica_id} ready on {replica.address} "
          f"(models: {sorted(replica.server.endpoints)})", flush=True)
    done.wait()
    replica.stop(drain_timeout_s=args.drain_timeout_s)
    print(f"replica {replica.replica_id}: drained and stopped.",
          flush=True)
    return 0


# ------------------------------------------------------------------- router
def cmd_router(args) -> int:
    from deeplearning4j_tpu.fleet import FleetRouter, FleetView
    view = FleetView(args.store, ttl_s=args.ttl_s)
    router = FleetRouter(view, port=args.port,
                         bind_address=args.bind).start()
    print(f"fleet router on {router.address} (store: {args.store})",
          flush=True)
    done = _wait_for_signal(
        lambda s: print(f"router: signal {s}, stopping", flush=True))
    done.wait()
    router.stop()
    print("router stopped.", flush=True)
    return 0


# ----------------------------------------------------------------------- up
def cmd_up(args) -> int:
    from deeplearning4j_tpu.fleet import FleetRouter, FleetView

    here = os.path.abspath(__file__)
    procs = []
    for i in range(args.replicas):
        cmd = [sys.executable, here, "replica", "--store", args.store,
               "--replica-id", f"rep{i}", "--ttl-s", str(args.ttl_s),
               "--drain-timeout-s", str(args.drain_timeout_s)]
        for name, ckpt in args.model:
            cmd += ["--model", f"{name}={ckpt}"]
        if args.poll_secs is not None:
            cmd += ["--poll-secs", str(args.poll_secs)]
        procs.append(subprocess.Popen(cmd))
    view = FleetView(args.store, ttl_s=args.ttl_s)
    router = FleetRouter(view, port=args.router_port,
                         bind_address=args.bind).start()
    print(f"fleet router on {router.address}; {args.replicas} replica(s) "
          "warming (readyz flips when the first lease is warmed)",
          flush=True)

    done = _wait_for_signal(
        lambda s: print(f"fleet: signal {s}, draining replicas",
                        flush=True))
    done.wait()
    # drain order: replicas first (each withdraws its lease, finishes
    # admitted work), router last — admitted requests complete, the
    # router 503s anything arriving after the last lease is gone
    for p in procs:
        p.send_signal(signal.SIGTERM)
    # wall-clock reap deadline, not a device stopwatch
    deadline = time.monotonic() + args.drain_timeout_s + 30.0  # lint: disable=DLT003
    for p in procs:
        try:
            p.wait(timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
    router.stop()
    print("fleet stopped.", flush=True)
    return 0


# -------------------------------------------------------------------- smoke
def cmd_smoke(args) -> int:
    """In-process end-to-end: 2 replicas on an in-memory store, health-
    aware routing, retry-over-replica-death. No checkpoints required."""
    import numpy as np
    from deeplearning4j_tpu.checkpoint.storage import ObjectStoreBackend
    from deeplearning4j_tpu.fleet import FleetRouter, FleetView, ServingReplica
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.serving import ModelServer

    def _net(seed):
        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater(Sgd(learning_rate=0.05)).weight_init("xavier")
                .list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3, loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    store = ObjectStoreBackend()
    example = np.zeros((4, 4), np.float32)
    replicas = []
    try:
        for i in range(2):
            srv = ModelServer(port=0)
            srv.add_model("smoke", _net(i), warmup_example=example)
            replicas.append(ServingReplica(
                srv, store, f"smoke{i}", ttl_s=5.0,
                heartbeat_s=0.5).start())
        for r in replicas:
            assert r.wait_ready(120), "replica never warmed"
        router = FleetRouter(FleetView(store), refresh_s=0.1,
                             seed=0).start()
        try:
            body = json.dumps(
                {"inputs": [[5.1, 3.5, 1.4, 0.2]]}).encode()
            req = urllib.request.Request(
                router.address + "/v1/models/smoke:predict", data=body,
                headers={"Content-Type": "application/json"})
            for _ in range(3):
                with urllib.request.urlopen(req, timeout=15) as resp:
                    assert resp.status == 200
            # ungraceful replica death: routing retries to the survivor
            replicas[0].server.stop(drain=False)
            with urllib.request.urlopen(req, timeout=15) as resp:
                assert resp.status == 200
            with urllib.request.urlopen(router.address + "/v1/fleet",
                                        timeout=5) as resp:
                assert resp.status == 200
        finally:
            router.stop()
    finally:
        for r in replicas:
            try:
                r.stop(drain_timeout_s=5.0)
            except Exception:
                pass
    print("fleet smoke: OK", flush=True)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return {"up": cmd_up, "replica": cmd_replica,
            "router": cmd_router, "smoke": cmd_smoke}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
