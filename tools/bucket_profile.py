"""Bucket per-step HLO self-times from the captured xplane.
PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python python tools/bucket_profile.py [xplane.pb]
"""
import collections
import glob
import os
import re
import sys

from tensorflow.tsl.profiler.protobuf import xplane_pb2

STEPS = 10


def bucket(name):
    head = name.split(" = ")[0]
    # what the op DOES is the first token after '=': e.g. 'fusion(', 'copy('
    m = re.search(r"= \S+ ([a-z\-_.]+)\(", name)
    kind = m.group(1) if m else "?"
    if "convolution" in head or kind == "convolution":
        return "conv-raw"
    if "multiply_reduce_fusion" in head:
        return "bn-reduce"
    if "select-and-scatter" in head or kind == "select-and-scatter":
        return "maxpool-bwd"
    if "copy" in head or kind.startswith("copy"):
        return "copy"
    if "add_add_fusion" in head:
        return "residual-add"
    if "reduce" in head:
        return "other-reduce"
    if "fusion" in head:
        # classify fusions by their first output's dtype and rank (rank<=1
        # f32 outputs are per-leaf optimizer/param updates; higher-rank f32
        # outputs are dW convs and their fused consumers)
        m2 = re.match(r"%\S+ = \(?((?:bf16|f32|s32|pred|u32)\[[^\]]*\])", name)
        out = m2.group(1) if m2 else "?"
        if out.startswith("f32["):
            return ("fusion-f32-small" if out.count(",") <= 1
                    else "fusion-f32-big")
        return "fusion-" + (out[:4] if out != "?" else "?")
    return kind


def main():
    if len(sys.argv) > 1:
        xp = sys.argv[1]
    else:
        xp = sorted(glob.glob(os.path.join(
            os.path.dirname(__file__), "profile_out",
            "**", "*.xplane.pb"), recursive=True))[-1]
    space = xplane_pb2.XSpace()
    with open(xp, "rb") as f:
        space.ParseFromString(f.read())
    for plane in space.planes:
        if "TPU" not in plane.name:
            continue
        emeta = plane.event_metadata
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            agg = collections.defaultdict(float)
            cnt = collections.Counter()
            for ev in line.events:
                name = emeta[ev.metadata_id].name
                b = bucket(name)
                agg[b] += ev.duration_ps / 1e12
                cnt[b] += 1
            total = sum(agg.values())
            print(f"total on-device: {total/STEPS*1e3:.2f} ms/step")
            for b, t in sorted(agg.items(), key=lambda kv: -kv[1]):
                print(f"  {t/STEPS*1e3:7.2f} ms/step  x{cnt[b]//STEPS:<5d} {b}")


if __name__ == "__main__":
    main()
