"""Capture a jax.profiler trace of the ResNet50 train step and print the
top self-time ops from the xplane. PYTHONPATH=. python tools/perf_resnet_profile.py
"""
import dataclasses as dc
import glob
import os

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.nn.graph import ComputationGraph

OUT = os.path.join(os.path.dirname(__file__), "profile_out")


def main():
    batch = 128
    conf = dc.replace(
        ResNet50(num_classes=1000, input_shape=(224, 224, 3)).conf(),
        dtype="bfloat16")
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 224, 224, 3), np.float32))
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, batch)])
    step = net._get_jitted("train")
    loss = [None]

    def run_one():
        net._rng, k = jax.random.split(net._rng)
        net.params, net.state, net.opt_state, loss[0] = step(
            net.params, net.state, net.opt_state, k, [x], [y], None, None)
    for _ in range(5):
        run_one()
    float(loss[0])
    with jax.profiler.trace(OUT):
        for _ in range(10):
            run_one()
        float(loss[0])
    print("trace captured to", OUT)
    for f in glob.glob(OUT + "/**/*.xplane.pb", recursive=True):
        print("xplane:", f, os.path.getsize(f))


if __name__ == "__main__":
    main()
