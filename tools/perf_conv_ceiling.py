"""Time ONLY ResNet50's convolutions (fwd + dW + dX) to find the conv ceiling.

Pulls every ConvolutionLayer out of the real graph config with its true input
shape, then times one jitted program that runs them all and their gradients.
PYTHONPATH=. python tools/perf_conv_ceiling.py
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.nn.conf.convolutional import ConvolutionLayer
from deeplearning4j_tpu.nn.graph import ComputationGraph

BATCH = 128
PEAK = 197e12


def main():
    conf = ResNet50(num_classes=1000, input_shape=(224, 224, 3)).conf()
    net = ComputationGraph(conf)
    convs = []
    total_flops = 0
    for name in net.order:
        obj, _ = net.vertices[name]
        if isinstance(obj, ConvolutionLayer):
            it = net.vertex_input_types[name][0]
            out_t = obj.output_type(it)
            kh, kw = obj.kernel_size
            flops = 2 * BATCH * out_t.height * out_t.width * obj.n_out * \
                kh * kw * (obj.n_in or it.channels)
            total_flops += flops
            convs.append((name, (BATCH, it.height, it.width, it.channels),
                          (kh, kw, it.channels, obj.n_out), obj.stride,
                          obj.convolution_mode, obj.padding))
    print(f"{len(convs)} convs, fwd GFLOP/img: {total_flops/BATCH/1e9:.2f}")

    rng = np.random.default_rng(0)
    xs = [jnp.asarray(rng.standard_normal(s, np.float32), jnp.bfloat16)
          for _, s, _, _, _, _ in convs]
    ws = [jnp.asarray(rng.standard_normal(k, np.float32) * 0.05, jnp.bfloat16)
          for _, _, k, _, _, _ in convs]

    def loss(ws_, xs_):
        tot = 0.0
        for (name, _, _, stride, mode, pad), x, w in zip(convs, xs_, ws_):
            if mode == "same":
                padding = "SAME"
            else:
                padding = ((pad[0], pad[0]), (pad[1], pad[1]))
            z = lax.conv_general_dilated(
                x, w, window_strides=stride, padding=padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            tot = tot + jnp.mean(z.astype(jnp.float32))
        return tot

    gfn = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))
    out = [None]

    def run_one():
        out[0] = gfn(ws, xs)
    for _ in range(3):
        run_one()
    float(out[0][0])
    t0 = time.perf_counter()
    for _ in range(20):
        run_one()
    float(out[0][0])
    dt = (time.perf_counter() - t0) / 20
    print(f"conv fwd+dW+dX: {dt*1e3:.1f} ms | {3*total_flops/dt/1e12:.1f} TF/s "
          f"| mfu {3*total_flops/dt/PEAK:.3f}")

    # forward only
    ffn = jax.jit(loss)

    def run_f():
        out[0] = ffn(ws, xs)
    for _ in range(3):
        run_f()
    float(out[0])
    t0 = time.perf_counter()
    for _ in range(20):
        run_f()
    float(out[0])
    dt = (time.perf_counter() - t0) / 20
    print(f"conv fwd only : {dt*1e3:.1f} ms | {total_flops/dt/1e12:.1f} TF/s "
          f"| mfu(fwd) {total_flops/dt/PEAK:.3f}")


if __name__ == "__main__":
    main()
