"""Serve checkpointed models over HTTP (serving.ModelServer CLI).

The deployment shape the serving tier was built for: restore each model's
latest committed checkpoint, put it behind the overload-safe front-end,
and keep polling the store so a training job's newer commits hot-swap in
live (zero dropped requests, no recompiles).

    python tools/serve.py --model iris=/ckpts/iris --model mnist=/ckpts/mnist \
        --port 9100 --poll-secs 10 --queue-depth 256 --deadline-ms 250

Then::

    curl -s localhost:9100/readyz
    curl -s -X POST localhost:9100/v1/models/iris:predict \
        -d '{"inputs": [[5.1, 3.5, 1.4, 0.2]], "deadline_ms": 100}'
    curl -s localhost:9100/metrics | grep serving_

Admission (429), deadlines (504), breaker (503), drain-on-SIGTERM and
warmup-gated /readyz all per deeplearning4j_tpu/serving/server.py.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_model(spec: str):
    name, sep, path = spec.partition("=")
    if not sep or not name or not path:
        raise argparse.ArgumentTypeError(
            f"--model takes name=checkpoint_dir; got {spec!r}")
    return name, path


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", action="append", type=_parse_model,
                   default=[], metavar="NAME=CKPT_DIR",
                   help="model name + checkpoint directory (repeatable: "
                        "several nets behind one server)")
    p.add_argument("--generate", action="append", type=_parse_model,
                   default=[], metavar="NAME=CKPT_DIR",
                   help="generative (stateful RNN) model to serve behind "
                        "POST /v1/models/NAME:generate with token "
                        "streaming (repeatable); hot-swaps with "
                        "--poll-secs like --model")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persist XLA executables under DIR "
                        "(perf.compile_cache): the second cold start "
                        "replays warmup compiles from disk")
    p.add_argument("--port", type=int, default=9100)
    p.add_argument("--bind", default="127.0.0.1",
                   help="bind address (default loopback; 0.0.0.0 to "
                        "serve remotely)")
    p.add_argument("--queue-depth", type=int, default=256,
                   help="admission queue bound per model (full ⇒ 429)")
    p.add_argument("--deadline-ms", type=float, default=1000.0,
                   help="default per-request deadline")
    p.add_argument("--batch-limit", type=int, default=32,
                   help="max coalesced requests per dispatch")
    p.add_argument("--poll-secs", type=float, default=None,
                   help="checkpoint hot-swap poll cadence (off when unset)")
    p.add_argument("--fold-bn", action="store_true",
                   help="serve BN-folded copies (perf.fusion.fold_bn)")
    p.add_argument("--quantize", action="store_true",
                   help="serve int8-quantized copies (quant/): each model's "
                        "checkpoint dir must hold a calibration.json "
                        "(written by tools/quantize.py --save-calibration); "
                        "hot-swapped checkpoints are re-quantized with the "
                        "same record")
    p.add_argument("--drain-timeout-s", type=float, default=30.0)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not args.model and not args.generate:
        print("error: need at least one --model or --generate",
              file=sys.stderr)
        return 2
    from deeplearning4j_tpu.checkpoint import CheckpointManager
    from deeplearning4j_tpu.serving import ModelServer

    server = ModelServer(port=args.port, bind_address=args.bind,
                         default_deadline_ms=args.deadline_ms,
                         queue_depth=args.queue_depth,
                         batch_limit=args.batch_limit,
                         compile_cache_dir=args.compile_cache)
    managers = []
    for name, ckpt_dir in args.model:
        cm = CheckpointManager(ckpt_dir)
        managers.append(cm)
        net = cm.restore_latest(load_updater=False)
        if net is None:
            print(f"error: no restorable checkpoint in {ckpt_dir!r} "
                  f"for model '{name}'", file=sys.stderr)
            return 2
        record = None
        if args.quantize:
            from deeplearning4j_tpu.quant import CalibrationRecord
            cal_path = os.path.join(ckpt_dir, "calibration.json")
            if not os.path.exists(cal_path):
                print(f"error: --quantize needs {cal_path!r} — run "
                      f"tools/quantize.py --ckpt {ckpt_dir} "
                      "--save-calibration first", file=sys.stderr)
                return 2
            record = CalibrationRecord.load(cal_path)
        try:
            server.add_model(name, net, fold_bn=args.fold_bn,
                             quantize=record, checkpoint_manager=cm,
                             checkpoint_poll_secs=args.poll_secs)
        except (ValueError, TypeError) as e:
            # e.g. a stale calibration.json measured on a different
            # architecture than the checkpoint now restores to
            print(f"error: cannot serve model '{name}': {e}",
                  file=sys.stderr)
            return 2
        step = net._restored_from.step
        print(f"model '{name}': serving checkpoint step {step} "
              f"from {ckpt_dir}"
              + (" (int8-quantized)" if record is not None else ""),
              flush=True)

    for name, ckpt_dir in args.generate:
        cm = CheckpointManager(ckpt_dir)
        managers.append(cm)
        net = cm.restore_latest(load_updater=False)
        if net is None:
            print(f"error: no restorable checkpoint in {ckpt_dir!r} "
                  f"for generator '{name}'", file=sys.stderr)
            return 2
        try:
            server.add_generator(
                name, net,
                checkpoint_manager=cm if args.poll_secs else None,
                checkpoint_poll_secs=args.poll_secs)
        except (ValueError, TypeError) as e:
            print(f"error: cannot serve generator '{name}': {e}",
                  file=sys.stderr)
            return 2
        print(f"generator '{name}': serving checkpoint step "
              f"{net._restored_from.step} from {ckpt_dir}", flush=True)

    # predict endpoints have no example shape on file (first-request
    # compiles); decode slot ladders DO warm — async, gating /readyz
    server.start(warmup=bool(server.generators))
    print(f"serving {len(server.endpoints)} model(s) + "
          f"{len(server.generators)} generator(s) on "
          f"{server.address} (hot-swap "
          f"{'every %gs' % args.poll_secs if args.poll_secs else 'off'})",
          flush=True)

    done = threading.Event()

    def _shutdown(signum, frame):
        print(f"signal {signum}: draining "
              f"({server.inflight} in flight)...", flush=True)
        done.set()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    done.wait()
    server.stop(drain=True, drain_timeout_s=args.drain_timeout_s)
    for cm in managers:
        cm.close()
    print("drained and stopped.", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
