"""Offline post-training int8 quantization CLI (quant/ subsystem).

Load a checkpoint (a ``CheckpointManager`` directory or a model zip), run
activation-range calibration from a dataset spec, lower to the int8
serving graph, and emit the quantized model zip + a calibration report::

    python tools/quantize.py --ckpt /ckpts/mnist --out mnist_int8.zip \
        --data random:784 --batches 16 --batch-size 32 \
        --observer percentile --percentile 99.99

The quantized zip restores into the exact quantized predict
(``deeplearning4j_tpu.utils.serialization.restore``) and can be served
directly. ``--save-calibration`` additionally drops ``calibration.json``
into the checkpoint DIRECTORY — that is what ``tools/serve.py --model
name=ckpt_dir --quantize`` reads to serve the int8 lowering live (and
re-apply it to every newer checkpoint the trainer commits).

Dataset specs (``--data``):

- ``random:<d0>x<d1>x...[@seed]`` — standard-normal batches of that
  per-example shape (e.g. ``random:784`` flat, ``random:28x28x1`` image)
- ``path.npz[:key]`` — an array from an .npz archive (default key ``x``)
- ``path.npy`` — a raw array; the leading axis is split into batches

Calibration data should be REPRESENTATIVE of serving traffic — random
data gives structurally valid scales for smoke tests, not accuracy-
preserving ones. ``--eval`` runs the accuracy gate (quant.accuracy_delta)
over the same stream when it carries labels (npz with ``y``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _load_net(path: str):
    if os.path.isdir(path):
        from deeplearning4j_tpu.checkpoint import CheckpointManager
        cm = CheckpointManager(path)
        try:
            net = cm.restore_latest(load_updater=False)
        finally:
            cm.close()
        if net is None:
            raise SystemExit(f"error: no restorable checkpoint in {path!r}")
        return net
    from deeplearning4j_tpu.utils.serialization import restore
    return restore(path)


def parse_data_spec(spec: str, batches: int, batch_size: int):
    """Yield feature batches for a --data spec (see module docstring)."""
    if spec.startswith("random:"):
        body = spec[len("random:"):]
        seed = 0
        if "@" in body:
            body, seed_s = body.rsplit("@", 1)
            seed = int(seed_s)
        dims = tuple(int(d) for d in body.split("x"))
        rng = np.random.default_rng(seed)
        return [rng.standard_normal((batch_size,) + dims).astype(np.float32)
                for _ in range(batches)], None
    key = "x"
    path = spec
    if ":" in spec and not os.path.exists(spec):
        path, key = spec.rsplit(":", 1)
    if path.endswith(".npz"):
        with np.load(path) as z:
            x = np.asarray(z[key], np.float32)
            y = np.asarray(z["y"], np.float32) if "y" in z.files else None
    else:
        x = np.asarray(np.load(path), np.float32)
        y = None
    n = max(1, min(batches, -(-len(x) // batch_size)))
    xs = [x[i * batch_size:(i + 1) * batch_size] for i in range(n)]
    ys = (None if y is None else
          [y[i * batch_size:(i + 1) * batch_size] for i in range(n)])
    return [b for b in xs if len(b)], ys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--ckpt", required=True,
                   help="CheckpointManager directory or model zip to load")
    p.add_argument("--out", required=True, help="quantized model zip path")
    p.add_argument("--data", required=True,
                   help="calibration dataset spec (random:<dims>[@seed], "
                        ".npz[:key], .npy)")
    p.add_argument("--batches", type=int, default=16)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--observer", choices=("minmax", "percentile"),
                   default="minmax")
    p.add_argument("--percentile", type=float, default=99.99)
    p.add_argument("--report", default=None,
                   help="calibration report JSON path "
                        "(default: <out>.report.json)")
    p.add_argument("--save-calibration", action="store_true",
                   help="also write calibration.json into the checkpoint "
                        "directory (what tools/serve.py --quantize reads)")
    p.add_argument("--eval", action="store_true",
                   help="run the accuracy gate over the calibration "
                        "stream (needs labels: npz with 'y')")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from deeplearning4j_tpu.quant import (accuracy_delta, calibrate,
                                          param_bytes, quantize,
                                          quantized_layers)
    from deeplearning4j_tpu.utils.serialization import write_model

    net = _load_net(args.ckpt)
    xs, ys = parse_data_spec(args.data, args.batches, args.batch_size)
    record = calibrate(net, xs, observer=args.observer,
                       percentile=args.percentile)
    qnet = quantize(net, record)
    write_model(qnet, args.out, save_updater=False)

    fp32_bytes = param_bytes(net)
    q_bytes = param_bytes(qnet)
    report = {
        "source": args.ckpt,
        "out": args.out,
        "observer": args.observer,
        "percentile": (args.percentile if args.observer == "percentile"
                       else None),
        "calibration_batches": record.batches,
        "quantized_layers": [k for k, _ in quantized_layers(qnet)],
        "fp32_param_bytes": fp32_bytes,
        "quantized_param_bytes": q_bytes,
        "byte_reduction_x": round(fp32_bytes / max(q_bytes, 1), 2),
        "ranges": record.ranges,
    }
    if args.eval:
        if ys is None:
            print("warning: --eval needs labels (npz with 'y'); skipping "
                  "the accuracy gate", file=sys.stderr)
        else:
            from deeplearning4j_tpu.datasets.dataset import DataSet
            stream = [DataSet(x, y) for x, y in zip(xs, ys)]
            report["accuracy"] = accuracy_delta(net, qnet, stream)
    report_path = args.report or (args.out + ".report.json")
    with open(report_path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    if args.save_calibration:
        if os.path.isdir(args.ckpt):
            record.save(os.path.join(args.ckpt, "calibration.json"))
        else:
            print("warning: --save-calibration needs --ckpt to be a "
                  "checkpoint DIRECTORY (tools/serve.py --quantize reads "
                  "calibration.json from there); nothing written for "
                  f"model zip {args.ckpt!r}", file=sys.stderr)
    print(json.dumps({
        "quantized": len(report["quantized_layers"]),
        "byte_reduction_x": report["byte_reduction_x"],
        "out": args.out, "report": report_path,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
