"""Offline vector-index build CLI (retrieval/ subsystem).

Build a device-servable index from a vector source, gate its recall, and
save the ``.npz`` that ``retrieval.load_index`` (and a serving replica's
hot-swap rebuild) loads in milliseconds::

    python tools/build_index.py --vectors corpus.npy --kind ivf \
        --int8 --nprobe 8 --out words.idx.npz --gate-min-recall 0.95

    # compression rungs: --int4 (packed nibbles, half the int8 code
    # bytes), --pq M [--ksub K --rerank R] (PQ codebooks + ADC; with
    # --kind ivf -> IVF-PQ over residuals), --csr (IVF flat cell layout,
    # no dense padding waste); the summary prints bytes_per_vector
    python tools/build_index.py --vectors corpus.npy --kind ivf --pq 8 \
        --rerank 16 --out words.pq.npz --gate-min-recall 0.95

    # smoke-query the saved index
    python tools/build_index.py --load words.idx.npz --query-random 4

Vector sources (``--vectors``):

- ``path.npy`` — a raw (n, d) float matrix
- ``path.npz[:key]`` — an array from an .npz archive (default key ``x``)
- ``random:<n>x<d>[@seed]`` — a synthetic clustered corpus (smoke tests)

``--gate-min-recall`` runs ``retrieval.assert_recall_within`` on a held-
out sample of the corpus itself before saving — a failed gate exits
nonzero and writes nothing, the quant-CLI precedent: an index that lost
too much recall never reaches serving.

Serve the result through ``serving.ModelServer.add_index`` (see README
"Vector retrieval" for the endpoint recipe and the hot-swap rebuild).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _load_vectors(spec: str) -> np.ndarray:
    if spec.startswith("random:"):
        from deeplearning4j_tpu.retrieval import synthetic_corpus
        body = spec[len("random:"):]
        seed = 0
        if "@" in body:
            body, s = body.rsplit("@", 1)
            seed = int(s)
        n, d = (int(x) for x in body.split("x"))
        return synthetic_corpus(n, d, seed=seed)
    path, _, key = spec.partition(":")
    if path.endswith(".npz"):
        with np.load(path) as z:
            return np.asarray(z[key or "x"], np.float32)
    return np.asarray(np.load(path), np.float32)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--vectors", help="vector source (npy/npz/random: spec)")
    p.add_argument("--kind", choices=("brute", "ivf"), default="ivf")
    p.add_argument("--metric", choices=("euclidean", "cosine"),
                   default="euclidean")
    p.add_argument("--int8", action="store_true",
                   help="int8-compress the table (quant/ symmetric grid)")
    p.add_argument("--int4", action="store_true",
                   help="int4-pack the table (two nibbles per byte, "
                        "half the int8 code bytes; quant/pack.py grid)")
    p.add_argument("--pq", type=int, default=None, metavar="M",
                   help="product-quantize into M subspaces (1 byte per "
                        "subspace per vector, ADC scoring); --kind brute "
                        "-> flat PQ, --kind ivf -> IVF-PQ over residuals")
    p.add_argument("--ksub", type=int, default=256,
                   help="PQ codewords per subspace (<= 256)")
    p.add_argument("--rerank", type=int, default=0, metavar="R",
                   help="compressed kinds: exact host-side re-rank of "
                        "the top R*k approximate candidates against the "
                        "fp32 table (kept host-side; recovers recall at "
                        "high compression)")
    p.add_argument("--csr", action="store_true",
                   help="IVF only: CSR cell layout (flat cell-major "
                        "rows + offsets — no dense cap-count padding "
                        "waste on skewed cells; IVF-PQ is CSR already)")
    p.add_argument("--observer", default=None,
                   choices=("minmax", "percentile"),
                   help="table-clip observer for --int8/--int4 "
                        "(default minmax; not a PQ knob)")
    p.add_argument("--n-cells", type=int, default=None,
                   help="IVF cells (default sqrt(n))")
    p.add_argument("--nprobe", type=int, default=8)
    p.add_argument("--seed", type=int, default=123)
    p.add_argument("--out", help="output .npz path")
    p.add_argument("--gate-min-recall", type=float, default=None,
                   help="recall@--gate-k floor asserted on a held-out "
                        "corpus sample before saving")
    p.add_argument("--gate-k", type=int, default=10)
    p.add_argument("--gate-queries", type=int, default=128)
    p.add_argument("--load", help="load an existing index instead of "
                                  "building")
    p.add_argument("--query-random", type=int, default=0, metavar="B",
                   help="smoke-query B random vectors and print results")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from deeplearning4j_tpu import retrieval

    if args.load:
        ix = retrieval.load_index(args.load)
        print(json.dumps({"loaded": args.load, **{
            k: v for k, v in ix.stats().items() if k != "compile_watch"}}))
    else:
        if not args.vectors:
            print("need --vectors (or --load)", file=sys.stderr)
            return 2
        if args.int8 and args.int4 or (args.pq and (args.int8 or args.int4)):
            print("--int8/--int4/--pq are one codec knob — pick one",
                  file=sys.stderr)
            return 2
        v = _load_vectors(args.vectors)
        kind = args.kind
        if args.csr and args.kind != "ivf":
            print("--csr is an IVF cell-layout knob (--kind ivf)",
                  file=sys.stderr)
            return 2
        if args.pq:
            if args.metric != "euclidean":
                # forward nothing silently: PQ codebooks are euclidean
                # centroids, and a mismatched gate oracle would judge
                # the wrong geometry
                print("--pq indexes are euclidean-only (codebooks are "
                      "euclidean centroids); drop --metric",
                      file=sys.stderr)
                return 2
            if args.observer:
                print("--observer is an int8/int4 clip knob; PQ "
                      "codebooks have no observer — drop it",
                      file=sys.stderr)
                return 2
            kind = "pq" if args.kind == "brute" else "ivf_pq"
            kwargs = dict(M=args.pq, ksub=args.ksub, rerank=args.rerank,
                          seed=args.seed)
            if kind == "ivf_pq":
                kwargs.update(n_cells=args.n_cells, nprobe=args.nprobe)
        else:
            kwargs = dict(metric=args.metric, int8=args.int8,
                          int4=args.int4, rerank=args.rerank,
                          observer=args.observer or "minmax")
            if args.kind == "ivf":
                kwargs.update(n_cells=args.n_cells, nprobe=args.nprobe,
                              seed=args.seed,
                              layout="csr" if args.csr else "dense")
        ix = retrieval.build_index(v, kind=kind, **kwargs)
        if args.gate_min_recall is not None:
            rng = np.random.default_rng(args.seed)
            q = v[rng.choice(len(v), min(args.gate_queries, len(v)),
                             replace=False)]
            exact = (retrieval.BruteForceIndex(v, metric=args.metric)
                     if (kind != "brute" or args.int8 or args.int4)
                     else None)
            try:
                report = retrieval.assert_recall_within(
                    ix, q, args.gate_k, min_recall=args.gate_min_recall,
                    exact=exact)
            except retrieval.RecallGateError as e:
                print(f"recall gate FAILED: {e}", file=sys.stderr)
                return 1
            print(json.dumps({"recall_gate": report}))
        st = {k: v2 for k, v2 in ix.stats().items()
              if k != "compile_watch"}
        print(json.dumps({"built": st}))
        if args.out:
            ix.save(args.out)
            print(json.dumps({"saved": args.out,
                              "bytes": os.path.getsize(args.out)}))
    if args.query_random:
        rng = np.random.default_rng(1)
        q = rng.standard_normal((args.query_random, ix.dim)).astype(
            np.float32)
        idx, dist = ix.search(q, min(5, ix.size))
        print(json.dumps({"query_smoke": {
            "indices": np.asarray(idx).tolist(),
            "distances": np.round(np.asarray(dist), 4).tolist()}}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
