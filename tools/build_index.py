"""Offline vector-index build CLI (retrieval/ subsystem).

Build a device-servable index from a vector source, gate its recall, and
save the ``.npz`` that ``retrieval.load_index`` (and a serving replica's
hot-swap rebuild) loads in milliseconds::

    python tools/build_index.py --vectors corpus.npy --kind ivf \
        --int8 --nprobe 8 --out words.idx.npz --gate-min-recall 0.95

    # smoke-query the saved index
    python tools/build_index.py --load words.idx.npz --query-random 4

Vector sources (``--vectors``):

- ``path.npy`` — a raw (n, d) float matrix
- ``path.npz[:key]`` — an array from an .npz archive (default key ``x``)
- ``random:<n>x<d>[@seed]`` — a synthetic clustered corpus (smoke tests)

``--gate-min-recall`` runs ``retrieval.assert_recall_within`` on a held-
out sample of the corpus itself before saving — a failed gate exits
nonzero and writes nothing, the quant-CLI precedent: an index that lost
too much recall never reaches serving.

Serve the result through ``serving.ModelServer.add_index`` (see README
"Vector retrieval" for the endpoint recipe and the hot-swap rebuild).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _load_vectors(spec: str) -> np.ndarray:
    if spec.startswith("random:"):
        from deeplearning4j_tpu.retrieval import synthetic_corpus
        body = spec[len("random:"):]
        seed = 0
        if "@" in body:
            body, s = body.rsplit("@", 1)
            seed = int(s)
        n, d = (int(x) for x in body.split("x"))
        return synthetic_corpus(n, d, seed=seed)
    path, _, key = spec.partition(":")
    if path.endswith(".npz"):
        with np.load(path) as z:
            return np.asarray(z[key or "x"], np.float32)
    return np.asarray(np.load(path), np.float32)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--vectors", help="vector source (npy/npz/random: spec)")
    p.add_argument("--kind", choices=("brute", "ivf"), default="ivf")
    p.add_argument("--metric", choices=("euclidean", "cosine"),
                   default="euclidean")
    p.add_argument("--int8", action="store_true",
                   help="int8-compress the table (quant/ symmetric grid)")
    p.add_argument("--observer", default="minmax",
                   choices=("minmax", "percentile"),
                   help="table-clip observer for --int8")
    p.add_argument("--n-cells", type=int, default=None,
                   help="IVF cells (default sqrt(n))")
    p.add_argument("--nprobe", type=int, default=8)
    p.add_argument("--seed", type=int, default=123)
    p.add_argument("--out", help="output .npz path")
    p.add_argument("--gate-min-recall", type=float, default=None,
                   help="recall@--gate-k floor asserted on a held-out "
                        "corpus sample before saving")
    p.add_argument("--gate-k", type=int, default=10)
    p.add_argument("--gate-queries", type=int, default=128)
    p.add_argument("--load", help="load an existing index instead of "
                                  "building")
    p.add_argument("--query-random", type=int, default=0, metavar="B",
                   help="smoke-query B random vectors and print results")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from deeplearning4j_tpu import retrieval

    if args.load:
        ix = retrieval.load_index(args.load)
        print(json.dumps({"loaded": args.load, **{
            k: v for k, v in ix.stats().items() if k != "compile_watch"}}))
    else:
        if not args.vectors:
            print("need --vectors (or --load)", file=sys.stderr)
            return 2
        v = _load_vectors(args.vectors)
        kwargs = dict(metric=args.metric, int8=args.int8,
                      observer=args.observer)
        if args.kind == "ivf":
            kwargs.update(n_cells=args.n_cells, nprobe=args.nprobe,
                          seed=args.seed)
        ix = retrieval.build_index(v, kind=args.kind, **kwargs)
        if args.gate_min_recall is not None:
            rng = np.random.default_rng(args.seed)
            q = v[rng.choice(len(v), min(args.gate_queries, len(v)),
                             replace=False)]
            exact = (retrieval.BruteForceIndex(v, metric=args.metric)
                     if (args.int8 or args.kind == "ivf") else None)
            try:
                report = retrieval.assert_recall_within(
                    ix, q, args.gate_k, min_recall=args.gate_min_recall,
                    exact=exact)
            except retrieval.RecallGateError as e:
                print(f"recall gate FAILED: {e}", file=sys.stderr)
                return 1
            print(json.dumps({"recall_gate": report}))
        st = {k: v2 for k, v2 in ix.stats().items()
              if k != "compile_watch"}
        print(json.dumps({"built": st}))
        if args.out:
            ix.save(args.out)
            print(json.dumps({"saved": args.out,
                              "bytes": os.path.getsize(args.out)}))
    if args.query_random:
        rng = np.random.default_rng(1)
        q = rng.standard_normal((args.query_random, ix.dim)).astype(
            np.float32)
        idx, dist = ix.search(q, min(5, ix.size))
        print(json.dumps({"query_smoke": {
            "indices": np.asarray(idx).tolist(),
            "distances": np.round(np.asarray(dist), 4).tolist()}}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
