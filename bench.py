"""Benchmark: LeNet MNIST training throughput on one TPU chip.

BASELINE configs[0] ("LeNet MultiLayerNetwork on MNIST, single chip"). The
reference repo publishes no numbers (BASELINE.md); ``vs_baseline`` is
reported against a nominal V100 nd4j-cuda LeNet throughput estimate so the
ratio is meaningful across rounds.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

# The reference publishes no LeNet numbers; this is the driver-era nominal
# V100 figure used as the fixed denominator across rounds.
NOMINAL_V100_LENET_IMGS_PER_SEC = 10_000.0

BATCH = 256
WARMUP_STEPS = 10
MEASURE_STEPS = 300


def main():
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.datasets.fetchers import synthetic_mnist
    from deeplearning4j_tpu.models import LeNet

    net = LeNet(num_classes=10).init()
    x_np, y_np = synthetic_mnist(BATCH * 4, seed=7)
    step = net._get_jitted("train")

    batches = []
    for i in range(4):
        sl = slice(i * BATCH, (i + 1) * BATCH)
        batches.append((jnp.asarray(x_np[sl]), jnp.asarray(y_np[sl])))

    def run_one(i):
        x, y = batches[i % len(batches)]
        net._rng, k = jax.random.split(net._rng)
        net.params, net.state, net.opt_state, loss = step(
            net.params, net.state, net.opt_state, k, x, y, None, None)
        return loss

    for i in range(WARMUP_STEPS):
        run_one(i)
    jax.block_until_ready(net.params)

    # steps pipeline asynchronously; blocking on the params chain at the end
    # measures sustained device throughput (per-step host sync would measure
    # tunnel round-trip latency instead)
    t0 = time.perf_counter()
    for i in range(MEASURE_STEPS):
        run_one(i)
    jax.block_until_ready(net.params)
    dt = time.perf_counter() - t0

    imgs_per_sec = MEASURE_STEPS * BATCH / dt
    print(json.dumps({
        "metric": "lenet_mnist_train_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 1),
        "unit": "imgs/sec",
        "vs_baseline": round(imgs_per_sec / NOMINAL_V100_LENET_IMGS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
