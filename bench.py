"""Benchmarks for the BASELINE configs, on one TPU chip.

Covers BASELINE.json configs[0]-[3] plus the serving microbench:
  0. LeNet MultiLayerNetwork on MNIST            -> imgs/sec
  1. ResNet50 ComputationGraph (north star)      -> imgs/sec (+ MFU estimate)
  2. GravesLSTM char-RNN (tBPTT windows)         -> chars/sec
  3. Word2Vec skip-gram negative sampling        -> words/sec
  4. ParallelInference serving (concurrent clients, mixed request sizes)
                                                 -> req/sec + p50/p99 latency,
                                                    batch-size summary, compiles
  4b. serving_load: open-loop Poisson HTTP load against serving.ModelServer
                                                 -> goodput, p50/p99, shed +
                                                    expired rates, occupancy
  5. Checkpoint overhead (checkpoint/ subsystem) -> steps/sec off vs async
                                                    vs sync save_every_n_steps

The reference repo publishes no numbers (BASELINE.md); each ``vs_baseline``
is reported against a fixed nominal V100-era denominator so the ratio is
meaningful across rounds.

Prints ONE JSON line per benchmark; the north-star ResNet50 line prints
last. Set BENCH_QUICK=1 for a tiny smoke run (CI / CPU);
BENCH_ONLY=lenet,serving (comma list of bench names) restricts which
benches run — the tier-1 smoke test uses it to exercise the bucketing +
prefetch hot paths end-to-end without paying for the ResNet compile.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from deeplearning4j_tpu.obs import Stopwatch

QUICK = os.environ.get("BENCH_QUICK") == "1"

# The axon tunnel's host-side conditions swing measured throughput by
# +-10-15% run to run (shared hosting); REPS timed repetitions with
# best-of selection report the chip's capability rather than host noise.
REPS = 1 if QUICK else 3

_REPS_NOTE = ("best of %d timed repetitions (tunnel host noise is "
              "+-10-15%% run to run)" % REPS)


def _best_of(fn):
    """fn() -> elapsed seconds; returns the fastest of REPS repetitions."""
    return min(fn() for _ in range(REPS))

# Nominal V100-era denominators (the reference publishes nothing; these are
# order-of-magnitude figures for the CUDA stacks of that generation).
NOMINAL = {
    "lenet": 10_000.0,      # imgs/sec, LeNet MNIST
    "resnet50": 360.0,      # imgs/sec, fp32 V100 ResNet50 ImageNet
    "charlstm": 100_000.0,  # chars/sec, cuDNN LSTM char-RNN
    "word2vec": 500_000.0,  # words/sec, multithreaded host SGNS
    "serving": 10_000.0,    # req/sec, nominal GPU dynamic-batching server
    "checkpoint": 1_000.0,  # steps/sec, nominal small-model step loop
    "resilience": 100.0,    # ms, nominal small-model restore/swap budget
    "elastic": 1_000.0,     # ms, nominal membership-transition budget
    "compression": 4.0,     # x, byte-reduction bar for the default
                            # threshold policy (the DCN-win acceptance)
    "quant": 4.0,           # x, ideal int8 model-byte reduction (the
                            # acceptance bar is >= 3x after scale/bias
                            # overhead)
    "data_plane": 1_000_000.0,  # records/sec, nominal host-side ETL
                                # throughput for small-record corpora
    "data_plane_claim": 1_000.0,  # us, nominal one-RTT object-store
                                  # lease claim budget
    "data_plane_wait": 10.0,    # %, nominal data-wait share of a fit
                                # epoch before prefetch tuning
    "data_lake": 1_000_000.0,   # records/sec, same host-ETL nominal as
                                # data_plane — the lake arms show what
                                # the wire + cache tiers cost vs it
    "data_lake_restore": 100.0,  # ms, nominal small-model restore budget
                                 # (the resilience figure, now per tier)
    "retrieval": 10_000.0,      # queries/sec, nominal GPU brute-force
                                # ANN server at ~100k vectors
    "autotune": 1.0,            # x, tuned-vs-default step-time ratio
                                # (>= 1 means the record's choice is at
                                # least as fast as the default execution)
    "fleet": 5.0,               # ms, nominal router-hop overhead budget
                                # (one lease-table lookup + one proxied
                                # loopback HTTP round trip)
    "fleet_scaleup": 10.0,      # s, nominal cold-replica time-to-ready
                                # (restore + TuningRecord ladder warmup,
                                # no serve-path compiles)
    "decode": 1_000.0,          # tokens/sec, nominal GPU streaming-decode
                                # aggregate for a small char-RNN serving
                                # tier (~1ms/token budget)
    "pallas": 1.0,              # x, identity denominator: bench_pallas
                                # metrics come in kernel-on/off PAIRS and
                                # the on-arm's speedup_vs_off field is the
                                # signal, not vs_baseline
}


def emit(metric, value, unit, baseline_key, **extra):
    line = {"metric": metric, "value": round(value, 1), "unit": unit,
            "vs_baseline": round(value / NOMINAL[baseline_key], 3)}
    line.update(extra)
    print(json.dumps(line), flush=True)


def bench_lenet():
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.datasets.fetchers import synthetic_mnist
    from deeplearning4j_tpu.models import LeNet

    batch = 64 if QUICK else 256
    # LeNet at batch 256 is dispatch-rate-bound through the tunnel
    # (~2.5 ms/dispatch vs ~1 ms compute): train through fit_fused, the
    # framework's scan-fused multi-batch step (exactly equivalent math,
    # one dispatch per GROUP of minibatches)
    group = 2 if QUICK else 25
    n_groups, warmup_groups = (2, 1) if QUICK else (12, 2)
    net = LeNet(num_classes=10).init()
    x_np, y_np = synthetic_mnist(batch * 4, seed=7)
    xs = jnp.stack([jnp.asarray(x_np[(i % 4) * batch:(i % 4 + 1) * batch])
                    for i in range(group)])   # device-resident stack
    ys = jnp.stack([jnp.asarray(y_np[(i % 4) * batch:(i % 4 + 1) * batch])
                    for i in range(group)])

    def run_group():
        net.fit_fused((xs, ys))

    for _ in range(warmup_groups):
        run_group()
    float(net._score)

    def timed():
        t0 = time.perf_counter()
        for _ in range(n_groups):
            run_group()
        float(net._score)  # VALUE fetch forces the whole chain
        return time.perf_counter() - t0

    dt = _best_of(timed)
    emit("lenet_mnist_train_imgs_per_sec_per_chip",
         n_groups * group * batch / dt, "imgs/sec", "lenet",
         note="trained via fit_fused (scan-fused multi-batch step, exact "
              "same sequential-update math; LeNet was tunnel-dispatch-"
              "bound). Reference-equivalent per-batch fit() reported "
              "separately as ..._plain_fit. " + _REPS_NOTE)

    # plain per-batch fit(): reference MultiLayerNetwork.fit semantics —
    # one dispatch AND one listener firing per iteration (VERDICT r4 #7:
    # report both so the headline isn't an API users must opt into) — now
    # driven through DevicePrefetchIterator so batch N+1's device_put
    # overlaps step N (perf/prefetch.py)
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    ds = [DataSet(x_np[(i % 4) * batch:(i % 4 + 1) * batch],
                  y_np[(i % 4) * batch:(i % 4 + 1) * batch])
          for i in range(group)]
    it = ListDataSetIterator(ds, batch)
    net2 = LeNet(num_classes=10).init()
    net2.fit(it, prefetch=True)  # compile + warmup
    float(net2._score)

    def timed_plain():
        t0 = time.perf_counter()
        net2.fit(it, num_epochs=n_groups, prefetch=True)
        float(net2._score)
        return time.perf_counter() - t0

    dt2 = _best_of(timed_plain)
    emit("lenet_mnist_train_imgs_per_sec_per_chip_plain_fit",
         n_groups * group * batch / dt2, "imgs/sec", "lenet",
         compiles=net2.compile_watch.compiles(),
         dispatches=net2.compile_watch.dispatches(),
         note="reference-equivalent fit(): one dispatch + per-iteration "
              "listener semantics per minibatch, host->device transfer "
              "double-buffered via DevicePrefetchIterator; dispatch-rate-"
              "bound through the tunnel. " + _REPS_NOTE)


def _model_fwd_flops_per_image(net) -> float:
    """Forward FLOPs per image computed from the ACTUAL graph (convs +
    dense/output matmuls), counting one multiply-add as 2 FLOPs.

    Replaces the former hard-coded 4.1e9 constant, which was the standard
    ResNet50 multiply-ACCUMULATE count mislabelled as already-doubled FLOPs
    — it under-reported achieved TFLOP/s and MFU by ~1.88x (the true count
    for this graph is ~7.7e9). Methodology change recorded in the emitted
    ``note`` field (r4).
    """
    from deeplearning4j_tpu.nn.conf.convolutional import (
        ConvolutionLayer, FusedConvBNActivation)
    total = 0.0
    for name in net.order:
        obj, _ = net.vertices[name]
        it = net.vertex_input_types[name][0]
        if isinstance(obj, (ConvolutionLayer, FusedConvBNActivation)):
            from deeplearning4j_tpu.nn.conf.convolutional import _pair
            out_t = obj.output_type(it)
            kh, kw = _pair(obj.kernel_size)
            cin = obj.n_in or it.channels
            total += 2.0 * out_t.height * out_t.width * kh * kw * cin * obj.n_out
        elif hasattr(obj, "n_out") and hasattr(obj, "n_in") and \
                getattr(obj, "n_out", 0) and obj.__class__.__name__ in (
                    "DenseLayer", "OutputLayer"):
            n_in = obj.n_in or it.flat_size()
            total += 2.0 * n_in * obj.n_out
    return total


def _bench_resnet50_once(dtype: str, batch: int, side: int, warmup: int,
                         steps: int, fused: bool = False):
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models import ResNet50
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    conf = _dc.replace(
        ResNet50(num_classes=1000, input_shape=(side, side, 3)).conf(),
        dtype=dtype)
    if fused:
        conf = conf.fused()  # conv→BN→act fused blocks (perf/fusion.py)
    net = ComputationGraph(conf).init()
    fwd_flops = _model_fwd_flops_per_image(net)
    step = net._get_jitted("train")
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, side, side, 3), np.float32))
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.integers(0, 1000, batch)])
    loss = None

    def run_one():
        nonlocal loss
        net._rng, k = jax.random.split(net._rng)
        net.params, net.state, net.opt_state, loss = step(
            net.params, net.state, net.opt_state, k, [x], [y], None, None)

    for _ in range(warmup):
        run_one()
    float(loss)  # hard sync: a VALUE fetch, stronger than block_until_ready

    def timed():
        t0 = time.perf_counter()
        for _ in range(steps):
            run_one()
        float(loss)  # forces the whole dependency chain of the last step
        return time.perf_counter() - t0

    # best-of over the SAME compiled step (tunnel host noise, see _REPS_NOTE)
    return steps * batch / _best_of(timed), fwd_flops


def bench_resnet50():
    if QUICK:
        batch, side, warmup, steps = 2, 64, 1, 2
    else:
        batch = int(os.environ.get("BENCH_RESNET_BATCH", "128"))
        # warmup 6: the first few post-compile steps through the axon tunnel
        # run cold (queue/alloc warmth) and depressed the measurement ~3%
        side, warmup, steps = 224, 6, 30
    # Training FLOPs ~ 3x fwd (fwd + dX + dW). Fwd FLOPs are computed from
    # the actual graph in _model_fwd_flops_per_image. MFU denominator is
    # configurable (chip generations differ); default 197e12 = v5e bf16 peak.
    peak = float(os.environ.get("BENCH_PEAK_FLOPS", "197e12"))
    notes = {
        "float32": (
            "fp32 ablation (tools/PROFILE_r5.md): 'default' matmul "
            "precision already lowers f32 convs to single bf16 MXU passes "
            "(forcing true-f32 multi-pass costs a further 1.6x); the "
            "deficit vs bf16 is doubled HBM bytes per activation crossing "
            "in a bandwidth-bound step — bf16 compute with f32 master "
            "weights is the measured-optimal mode."),
        "bfloat16": (
            "step sits within ~5% of the measured bandwidth floor: conv "
            "fwd+dW+dX alone = 29.2 ms (51.4% MFU ceiling); the ~16 ms "
            "non-conv remainder is BN-train stats/normalize/residual + BN "
            "backward re-reads, ~4.7 full activation-set HBM crossings "
            "(tools/PROFILE_r5.md) — practical cap ~0.33 MFU on this XLA "
            "build. FLOPs computed from the graph, 2 FLOPs/MAC; value-"
            "fetch sync."),
    }
    # fp32 secondary line first; bf16 (the TPU-idiomatic compute dtype) is
    # the headline and prints LAST
    for dtype, metric in (
            ("float32", "resnet50_imagenet_train_imgs_per_sec_per_chip_fp32"),
            ("bfloat16", "resnet50_imagenet_train_imgs_per_sec_per_chip")):
        imgs_per_sec, fwd_flops = _bench_resnet50_once(
            dtype, batch, side, warmup, steps)
        achieved = imgs_per_sec * 3 * fwd_flops
        emit(metric, imgs_per_sec, "imgs/sec", "resnet50", batch=batch,
             dtype=dtype, achieved_tflops=round(achieved / 1e12, 2),
             mfu=round(achieved / peak, 4),
             fwd_gflops_per_img=round(fwd_flops / 1e9, 2),
             note=notes[dtype] + " " + _REPS_NOTE)


def bench_resnet50_fusion():
    """Fusion on/off ablation for the north-star model (perf/fusion.py):
    the same bf16 train step with the conv→BN→act chains left unfused vs
    rewritten into FusedConvBNActivation blocks whose custom-VJP BN
    backward recomputes x-hat instead of re-reading activation-sized
    saves. Emits one metric per mode (``..._fusion_{off,on}``) plus the
    jaxpr-derived training-activation-bytes each mode hands its backward —
    the HBM-traffic number the fusion attacks. Thresholds only on full
    runs (BASELINE notes): this hook exists so the next on-chip run
    records the attribution."""
    import dataclasses as _dc

    from deeplearning4j_tpu.models import ResNet50
    from deeplearning4j_tpu.perf.fusion import training_activation_bytes

    if QUICK:
        batch, side, warmup, steps = 2, 64, 1, 2
    else:
        batch = int(os.environ.get("BENCH_RESNET_BATCH", "128"))
        side, warmup, steps = 224, 6, 30
    peak = float(os.environ.get("BENCH_PEAK_FLOPS", "197e12"))
    conf = _dc.replace(
        ResNet50(num_classes=1000, input_shape=(side, side, 3)).conf(),
        dtype="bfloat16")
    act_bytes = {}
    for fused, tag in ((False, "off"), (True, "on")):
        c = conf.fused() if fused else conf
        try:
            act_bytes[tag] = int(training_activation_bytes(c,
                                                           minibatch=batch))
        except Exception:
            act_bytes[tag] = None
    for fused, tag in ((False, "off"), (True, "on")):
        imgs_per_sec, fwd_flops = _bench_resnet50_once(
            "bfloat16", batch, side, warmup, steps, fused=fused)
        achieved = imgs_per_sec * 3 * fwd_flops
        emit(f"resnet50_imagenet_train_imgs_per_sec_per_chip_fusion_{tag}",
             imgs_per_sec, "imgs/sec", "resnet50", batch=batch,
             dtype="bfloat16", fusion=tag,
             achieved_tflops=round(achieved / 1e12, 2),
             mfu=round(achieved / peak, 4),
             training_activation_bytes=act_bytes[tag],
             note="fusion ablation (perf/fusion.py): identical math within "
                  "fp tolerance; training_activation_bytes is the "
                  "jaxpr-derived fwd->bwd residual set the BN-backward "
                  "traffic rides on. " + _REPS_NOTE)


def bench_graveslstm():
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models import TextGenerationLSTM

    vocab = 47
    if QUICK:
        batch, T, windows, groups = 8, 16, 2, 1
    else:
        # one long document per group, trained through fit_tbptt_fused
        # (scan-fused windows, exact per-window tBPTT math; the per-window
        # loop was tunnel-dispatch-bound)
        batch, T, windows, groups = 64, 50, 30, 3
    net = TextGenerationLSTM(total_unique_characters=vocab,
                             tbptt_length=T).init()
    rng = np.random.default_rng(0)
    seq_len = T * windows
    ids = rng.integers(0, vocab, (batch, seq_len))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids])
    y = jnp.asarray(np.eye(vocab, dtype=np.float32)[
        rng.integers(0, vocab, (batch, seq_len))])

    def run_group():
        net.fit_tbptt_fused(x, y)

    run_group()                       # compile + warmup
    float(net._score)

    def timed():
        t0 = time.perf_counter()
        for _ in range(groups):
            run_group()
        float(net._score)
        return time.perf_counter() - t0

    dt = _best_of(timed)
    emit("graveslstm_charrnn_train_chars_per_sec_per_chip",
         groups * batch * seq_len / dt, "chars/sec", "charlstm",
         note="fit_tbptt_fused (all windows of a batch scan-fused into "
              "one dispatch, exact per-window tBPTT math). " + _REPS_NOTE)


def bench_word2vec():
    from deeplearning4j_tpu.nlp import Word2Vec

    rng = np.random.default_rng(0)
    if QUICK:
        n_sent, sent_len, vocab_n, batch = 200, 10, 500, 1024
    else:
        # 500k-word corpus (r5, was 100k): the corpus-resident device path
        # has ~50 ms of fixed per-fit cost (tunnel RTTs + final loss
        # fetch); the old tiny corpus measured mostly that, not sustained
        # throughput. words/s is corpus-size-independent beyond this.
        n_sent, sent_len, vocab_n, batch = 25_000, 20, 10_000, 8192
    # zipf-ish unigram distribution over a synthetic vocab
    ranks = np.arange(1, vocab_n + 1, dtype=np.float64)
    probs = (1.0 / ranks) / np.sum(1.0 / ranks)
    words = np.array([f"w{i}" for i in range(vocab_n)])
    choice = rng.choice(vocab_n, (n_sent, sent_len), p=probs)
    sents = [" ".join(words[row]) for row in choice]
    model = Word2Vec(layer_size=128, window_size=5, negative=5, epochs=1,
                     batch_size=batch, min_word_frequency=1, seed=1)
    model.fit(sents)    # vocab + compile + warmup
    total_words = model.vocab.total_word_occurrences

    def timed():
        with Stopwatch() as sw:  # fit() syncs internally: vocab/vectors land on host
            model.fit(sents)
        return sw.seconds

    dt = _best_of(timed)
    emit("word2vec_sgns_train_words_per_sec_per_chip", total_words / dt,
         "words/sec", "word2vec",
         note="r5: corpus-resident device training — encoded corpus ships "
              "to HBM once (content-hash cached across fits/epochs, int16), "
              "pair windows AND negatives generated on-device from the "
              "unigram table (jax PRNG), shared-negative batches turn the "
              "negative accumulation into a dense matmul; segmented async "
              "dispatches overlap host indexing with device training. "
              "Throughput no longer scales with host->device bandwidth. "
              + _REPS_NOTE)


def bench_serving():
    """ParallelInference under concurrent clients with MIXED request sizes —
    the workload where shape bucketing pays: without it every distinct
    coalesced batch size is a fresh XLA compile mid-traffic. Reports
    latency percentiles, batch-size summary and compile counters in one
    JSON line (the shape-stability regression tripwire)."""
    import threading

    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.parallel import ParallelInference
    from deeplearning4j_tpu.perf import BucketPolicy

    if QUICK:
        n_clients, reqs_per_client, batch_limit, hidden = 4, 4, 16, 32
    else:
        n_clients, reqs_per_client, batch_limit, hidden = 16, 16, 32, 256
    n_features, n_classes = 784, 10
    conf = (NeuralNetConfiguration.builder()
            .seed(11).updater(Sgd(learning_rate=0.01)).weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=n_classes, loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_features))
            .build())
    net = MultiLayerNetwork(conf).init()
    policy = BucketPolicy(floor=8)
    pi = ParallelInference(net, batch_limit=batch_limit, queue_timeout_ms=3,
                           bucket_policy=policy)
    sizes = [1, 3, 7, 20, 4, 12, 2, 32][: max(4, batch_limit // 4)]
    # pre-compile every bucket BEFORE traffic (the serving contract).
    # batch_limit caps coalesced REQUESTS, not rows: the worst-case
    # dispatch is every in-flight client's largest request in one batch.
    max_rows = min(batch_limit, n_clients) * max(sizes)
    t0 = time.perf_counter()
    warmed = pi.warmup(np.zeros((1, n_features), np.float32),
                       buckets=policy.buckets_up_to(max_rows))
    warmup_s = time.perf_counter() - t0
    compiles_after_warmup = net.compile_watch.compiles()
    lat: list = []
    lat_lock = threading.Lock()

    def client(cid):
        r = np.random.default_rng(cid)
        for i in range(reqs_per_client):
            x = r.standard_normal(
                (sizes[(cid + i) % len(sizes)], n_features)).astype(np.float32)
            sw = Stopwatch().start()
            sw.stop(pi.output_batched(x))  # blocks on the observable's host array
            with lat_lock:
                lat.append(sw.seconds)

    def timed():
        sw = Stopwatch().start()  # joins client threads; every client is synced
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        return sw.stop()

    dt = _best_of(timed)
    n_requests = n_clients * reqs_per_client
    st = pi.stats()
    pi.shutdown()
    emit("parallel_inference_serving_reqs_per_sec", n_requests / dt,
         "req/sec", "serving",
         p50_ms=round(float(np.percentile(lat, 50)) * 1000, 2),
         p99_ms=round(float(np.percentile(lat, 99)) * 1000, 2),
         requests=n_requests,
         batches_dispatched=st["batches_dispatched"],
         batch_size=st["batch_size"],
         warmed_buckets=warmed,
         warmup_s=round(warmup_s, 2),
         compiles=st.get("model_compiles"),
         compiles_after_warmup=compiles_after_warmup,
         unwarmed_dispatches=st["unwarmed_dispatches"],
         note="concurrent clients, request sizes cycling %s; every dispatch "
              "pads to a warmed bucket, so compiles == compiles_after_warmup "
              "must hold (shape-stability tripwire). " % sizes + _REPS_NOTE)


def bench_serving_load():
    """Open-loop serving load bench against the serving/ HTTP front-end:
    seeded POISSON arrivals at a configured offered load. Unlike the
    closed-loop bench_serving clients (whose arrival rate collapses to
    the service rate the moment the server slows), an open-loop generator
    keeps offering load under overload — which is exactly what exposes
    the admission-control story: goodput, p50/p99 latency, shed rate
    (429s), deadline expiries (504s) and batch occupancy at the offered
    rate. Metrics only on container runs per the 9p/bench-sensitivity
    note; thresholds belong to quiet full runs."""
    import threading
    import urllib.error
    import urllib.request

    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.serving import ModelServer

    if QUICK:
        offered_rps, duration_s, deadline_ms, hidden = 60.0, 1.2, 1000.0, 32
    else:
        offered_rps, duration_s, deadline_ms, hidden = 400.0, 5.0, 250.0, 256
    n_features, n_classes = 784, 10
    conf = (NeuralNetConfiguration.builder()
            .seed(11).updater(Sgd(learning_rate=0.01)).weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=n_classes, loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_features))
            .build())
    net = MultiLayerNetwork(conf).init()
    sizes = [1, 2, 4, 8]
    srv = ModelServer(default_deadline_ms=deadline_ms)
    ep = srv.add_model("mlp", net, queue_depth=64,
                       warmup_example=np.zeros((1, n_features), np.float32))
    # worst coalesced dispatch = batch_limit requests of the largest size;
    # warm the whole ladder so no live request pays an XLA compile
    ep.warmup_buckets = ep.pi.bucket_policy.buckets_up_to(
        ep.pi.batch_limit * max(sizes))
    srv.start(warmup_async=False)  # /readyz gating: ladder compiled first
    url = srv.address + "/v1/models/mlp:predict"
    # both predict encodings ride the same load mix: JSON float lists and
    # the binary wire format (base64 little-endian raw arrays). The byte
    # accounting below is exact for the request tensors in play; int8 is
    # the quantized-endpoint payload (same base64 framing, 1 byte/elem).
    import base64

    rng_x = np.random.default_rng(77)
    xs = [rng_x.standard_normal((s, n_features)).astype(np.float32)
          for s in sizes]
    payloads_json = [json.dumps({"inputs": x.tolist()}).encode()
                     for x in xs]
    payloads_b64 = [json.dumps(
        {"x_b64": base64.b64encode(x.tobytes()).decode(),
         "dtype": "float32", "shape": list(x.shape)}).encode() for x in xs]
    int8_bytes = [len(json.dumps(
        {"x_b64": base64.b64encode(
            x.astype(np.int8).tobytes()).decode(),
         "dtype": "int8", "shape": list(x.shape)}).encode()) for x in xs]
    json_bytes = sum(len(p) for p in payloads_json) / len(sizes)
    b64_bytes = sum(len(p) for p in payloads_b64) / len(sizes)
    i8_bytes = sum(int8_bytes) / len(sizes)
    # alternate encodings request to request: the binary decode path is
    # exercised under the same offered load as the JSON path
    payloads = [p for pair in zip(payloads_json, payloads_b64)
                for p in pair]
    results: list = []
    res_lock = threading.Lock()

    def fire(body):
        sw = Stopwatch().start()
        try:
            with urllib.request.urlopen(urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=30) as r:
                r.read()
                code = r.status
        except urllib.error.HTTPError as e:
            e.read()
            code = e.code
        except Exception:
            code = -1
        sw.stop()  # the HTTP response IS host-synced data
        with res_lock:
            results.append((code, sw.seconds))

    # the arrival schedule is drawn up front (seeded), then replayed on
    # the wall clock: arrivals never wait for completions (open loop)
    rng = np.random.default_rng(1234)
    arrivals, t = [], 0.0
    while True:
        t += float(rng.exponential(1.0 / offered_rps))
        if t >= duration_s:
            break
        arrivals.append(t)
    threads = []
    sw_run = Stopwatch().start()
    start = time.perf_counter()
    for i, at in enumerate(arrivals):
        delay = start + at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=fire,
                              args=(payloads[i % len(payloads)],),
                              daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=60)
    wall = float(sw_run.stop())  # client threads joined: host-synced

    codes = [c for c, _ in results]
    ok_lat = [l * 1000.0 for c, l in results if c == 200]
    shed = codes.count(429)
    expired = codes.count(504)
    other = sum(1 for c in codes if c not in (200, 429, 504))
    st = srv.endpoints["mlp"].stats()
    srv.stop(drain=True)
    n = max(1, len(results))
    emit("serving_load_goodput_reqs_per_sec", len(ok_lat) / wall,
         "req/sec", "serving",
         offered_rps=offered_rps,
         arrivals=len(arrivals),
         ok=len(ok_lat), shed=shed, expired=expired, other=other,
         shed_rate=round(shed / n, 3),
         expired_rate=round(expired / n, 3),
         p50_ms=(round(float(np.percentile(ok_lat, 50)), 2)
                 if ok_lat else None),
         p99_ms=(round(float(np.percentile(ok_lat, 99)), 2)
                 if ok_lat else None),
         batch_occupancy=st["batch_size"],
         queue=st["queue"],
         payload_bytes={
             "json_f32": round(json_bytes),
             "b64_f32": round(b64_bytes),
             "b64_int8": round(i8_bytes),
             "json_to_b64_x": round(json_bytes / b64_bytes, 2),
             "json_to_int8_x": round(json_bytes / i8_bytes, 2),
         },
         note="open-loop seeded Poisson arrivals over HTTP at the offered "
              "rate (request sizes cycling %s, deadline %gms, JSON and "
              "binary-b64 encodings alternating); shed = 429 admission "
              "rejections, expired = 504 deadline evictions. payload_bytes "
              "= mean request body size per encoding over the size mix "
              "(b64_int8 is the quantized-endpoint wire format). "
              "metrics only — thresholds on quiet full runs per the 9p "
              "note. " % (sizes, deadline_ms) + _REPS_NOTE)


def bench_decode():
    """Generative decode tier: aggregate tokens/sec with N concurrent
    sessions through the continuous-batching DecodeEngine (one jitted
    step advances every active session per dispatch, sessions joining at
    token boundaries) vs the sequential per-session ``rnn_time_step``
    loop on the SAME model — the measured-throughput gap ISSUE 19 exists
    to close. Also reports client-side time-to-first-token and
    inter-token-latency p50/p99 and the steady-state compile count
    (must be zero: every program warmed before the measured wave)."""
    import threading

    from deeplearning4j_tpu.models.textgenlstm import TextGenerationLSTM
    from deeplearning4j_tpu.serving.decode import DecodeEngine

    vocab = 16 if QUICK else 64
    units = 16 if QUICK else 96
    n_sessions = 8 if QUICK else 32
    gen_tokens = 16 if QUICK else 128
    prompt_len = 3 if QUICK else 12
    net = TextGenerationLSTM(total_unique_characters=vocab, units=units,
                             seed=7).init()
    rng = np.random.default_rng(0)
    prompts = [[int(t) for t in rng.integers(0, vocab, prompt_len)]
               for _ in range(n_sessions)]

    # ---- baseline: sequential greedy decode, one session at a time on
    # the stateful host API (prefill = step through the prompt, then
    # closed-loop argmax) — warmed first so both sides are steady-state
    def one_hot(tok):
        x = np.zeros((1, vocab), np.float32)
        x[0, tok] = 1.0
        return x

    net.rnn_clear_previous_state()
    net.rnn_time_step(one_hot(0))
    net.rnn_clear_previous_state()

    def seq_run():
        t0 = time.perf_counter()  # lint: disable=DLT003 (rnn_time_step returns HOST numpy — every step in the loop is already a device sync; int(argmax) consumes it)
        for prompt in prompts:
            net.rnn_clear_previous_state()
            for tok in prompt:
                out = net.rnn_time_step(one_hot(tok))
            cur = int(out[0].argmax())  # generated token 1
            for _ in range(gen_tokens - 1):
                out = net.rnn_time_step(one_hot(cur))
                cur = int(out[0].argmax())
        return time.perf_counter() - t0  # rnn_time_step returns host np

    seq_s = _best_of(seq_run)
    seq_tps = n_sessions * gen_tokens / seq_s

    # ---- continuous batching: N concurrent sessions, temperature 0
    # (greedy — the same per-token work as the baseline)
    engine = DecodeEngine(net, max_sessions=n_sessions,
                          min_slots=min(8, n_sessions),
                          prefill_buckets=(4, 16) if QUICK else (16, 64),
                          seed=1)
    engine.warmup()
    compiles_before = dict(engine.stats()["compiles"])

    def wave():
        ttfts, itls = [], []

        def consume(sess, opened):
            last = None
            for ev in sess.events(token_deadline_s=120.0):
                now = time.perf_counter()
                if ev["type"] != "token":
                    continue
                if last is None:
                    ttfts.append((now - opened) * 1e3)
                else:
                    itls.append((now - last) * 1e3)
                last = now

        threads = []
        t0 = time.perf_counter()  # lint: disable=DLT003 (clocks time CLIENT-side event arrival off the streaming queue — the engine worker's bulk readback synced the device before each event was emitted)
        for prompt in prompts:
            sess = engine.open_session(prompt, max_tokens=gen_tokens,
                                       temperature=0.0)
            th = threading.Thread(target=consume,
                                  args=(sess, time.perf_counter()),
                                  daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=300.0)
        return time.perf_counter() - t0, ttfts, itls

    best = None
    for _ in range(REPS):
        elapsed, ttfts, itls = wave()
        if best is None or elapsed < best[0]:
            best = (elapsed, ttfts, itls)
    eng_s, ttfts, itls = best
    eng_tps = n_sessions * gen_tokens / eng_s
    compiles_after = dict(engine.stats()["compiles"])
    steady = sum(compiles_after.values()) - sum(compiles_before.values())
    engine.stop()

    emit("decode_tokens_per_sec", eng_tps, "tokens/sec", "decode",
         sessions=n_sessions, tokens_per_session=gen_tokens,
         sequential_tokens_per_sec=round(seq_tps, 1),
         speedup_vs_sequential=round(eng_tps / seq_tps, 2),
         ttft_ms={"p50": round(float(np.percentile(ttfts, 50)), 2),
                  "p99": round(float(np.percentile(ttfts, 99)), 2)},
         itl_ms={"p50": round(float(np.percentile(itls, 50)), 2),
                 "p99": round(float(np.percentile(itls, 99)), 2)}
         if itls else None,
         compiles=compiles_after, steady_state_compiles=steady,
         note="aggregate greedy decode throughput, %d concurrent "
              "sessions x %d tokens through the device-resident session "
              "ladder vs the SAME model decoded sequentially per session "
              "via rnn_time_step; steady_state_compiles counts programs "
              "compiled during the measured wave (0 = every dispatch "
              "replayed a warmed program). " % (n_sessions, gen_tokens)
              + _REPS_NOTE)


def bench_fleet():
    """Fleet-tier overhead and elasticity: (a) predict p50/p99 direct to
    one ModelServer vs through the FleetRouter over 2 replicas — the
    router hop is one lease-table lookup + one proxied HTTP round trip,
    so the delta is the routing tax; (b) scale-up time-to-ready: lease
    write of a fresh (cold) replica → first 200 THROUGH the router for a
    model only that replica hosts — the instant-start story end to end
    (warmup off-path, lease flips only when ready, router never routes
    cold). Metrics only per the 9p/bench-sensitivity note."""
    import urllib.error
    import urllib.request

    from deeplearning4j_tpu.checkpoint.storage import ObjectStoreBackend
    from deeplearning4j_tpu.fleet import FleetRouter, FleetView, ServingReplica
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.serving import ModelServer

    n_requests, hidden = (30, 32) if QUICK else (200, 256)
    n_features, n_classes = 784, 10

    def _net(seed):
        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater(Sgd(learning_rate=0.01)).weight_init("xavier")
                .list()
                .layer(DenseLayer(n_out=hidden, activation="relu"))
                .layer(OutputLayer(n_out=n_classes, loss="mcxent"))
                .set_input_type(InputType.feed_forward(n_features))
                .build())
        return MultiLayerNetwork(conf).init()

    example = np.zeros((1, n_features), np.float32)
    rng = np.random.default_rng(77)
    body = json.dumps({"inputs": rng.standard_normal(
        (4, n_features)).astype(np.float32).tolist()}).encode()

    def _drive(url, n):
        lat = []
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        for _ in range(n):
            sw = Stopwatch().start()
            with urllib.request.urlopen(req, timeout=30) as r:
                r.read()
                assert r.status == 200
            lat.append(sw.stop() * 1000.0)
        return lat

    # (a) direct single server
    direct = ModelServer(port=0)
    direct.add_model("mlp", _net(0), warmup_example=example)
    direct.start(warmup_async=False)
    lat_direct = _drive(direct.address + "/v1/models/mlp:predict",
                        n_requests)
    direct.stop(drain=True)

    # (a) same model behind the router over 2 warmed replicas
    store = ObjectStoreBackend()
    replicas = []
    for i in range(2):
        srv = ModelServer(port=0)
        srv.add_model("mlp", _net(i), warmup_example=example)
        replicas.append(ServingReplica(srv, store, f"bench{i}",
                                       heartbeat_s=0.5).start())
    for r in replicas:
        r.wait_ready(300)
    router = FleetRouter(FleetView(store), refresh_s=0.1, seed=0).start()
    lat_routed = _drive(router.address + "/v1/models/mlp:predict",
                        n_requests)

    # (b) scale-up: cold replica hosting a model nothing else hosts;
    # clock runs from BEFORE its first lease write to the first 200
    # through the router
    srv3 = ModelServer(port=0)
    srv3.add_model("scaled", _net(7), warmup_example=example)
    rep3 = ServingReplica(srv3, store, "bench-scaleup", heartbeat_s=0.5)
    url3 = router.address + "/v1/models/scaled:predict"
    req3 = urllib.request.Request(
        url3, data=body, headers={"Content-Type": "application/json"})
    sw_up = Stopwatch().start()
    rep3.start()
    first_200_s = None
    deadline = time.perf_counter() + 300.0
    while time.perf_counter() < deadline:
        try:
            with urllib.request.urlopen(req3, timeout=10) as r:
                r.read()
                if r.status == 200:
                    first_200_s = float(sw_up.stop())
                    break
        except urllib.error.HTTPError as e:
            e.read()  # 503 no_replica until the lease flips warmed
        except Exception:
            pass
        time.sleep(0.05)

    router.stop()
    rep3.stop(drain_timeout_s=5.0)
    for r in replicas:
        r.stop(drain_timeout_s=5.0)

    p50_d = float(np.percentile(lat_direct, 50))
    p50_r = float(np.percentile(lat_routed, 50))
    emit("fleet_router_overhead_p50_ms", p50_r - p50_d, "ms", "fleet",
         direct_p50_ms=round(p50_d, 2),
         direct_p99_ms=round(float(np.percentile(lat_direct, 99)), 2),
         routed_p50_ms=round(p50_r, 2),
         routed_p99_ms=round(float(np.percentile(lat_routed, 99)), 2),
         requests=n_requests, replicas=2,
         note="sequential predicts, direct ModelServer vs through the "
              "FleetRouter (2 warmed replicas); the delta is the "
              "router hop. metrics only per the 9p note. " + _REPS_NOTE)
    emit("fleet_scale_up_time_to_ready_s",
         first_200_s if first_200_s is not None else -1.0,
         "s", "fleet_scaleup",
         note="cold replica start (lease write) -> first 200 through "
              "the router for a model only it hosts: warmup runs "
              "off-path and the lease flips warmed only when /readyz "
              "would pass, so this is the true scale-up latency the "
              "autoscaler pays. metrics only per the 9p note.")


def bench_checkpoint():
    """Checkpoint-overhead microbench: steps/sec for the same small-MLP
    train loop with checkpointing OFF, ASYNC every N steps (checkpoint/
    contract: snapshot on the training thread, write on a worker — the
    step loop must not pay for disk) and SYNC every N steps (the cost the
    async path hides). The acceptance bar is overhead_async_pct < 10 at
    save_every_n_steps=10."""
    import shutil
    import tempfile

    from deeplearning4j_tpu.checkpoint import CheckpointManager
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.updaters import Sgd

    # sized so the 10-step save interval (~150 ms at ~15 ms/step on CPU;
    # comparable for a real model on TPU) comfortably covers one atomic
    # commit (~15-20 ms for the ~0.3 MB payload on this host's 9p fs) —
    # the async path then hides the whole write. A model so small that
    # steps outrun the disk would instead measure the bounded queue's
    # BACKPRESSURE (by design: snapshots must not accumulate unboundedly
    # in host RAM), and on this CPU-only host the writer additionally
    # steals XLA compute cores, which a TPU deployment does not pay.
    steps = 40 if QUICK else 200
    batch, hidden = 2048, 256
    every = 10
    n_features, n_classes = 256, 10
    rng = np.random.default_rng(3)
    x = rng.standard_normal((batch, n_features)).astype(np.float32)
    y = np.eye(n_classes, dtype=np.float32)[rng.integers(0, n_classes, batch)]
    batches = [DataSet(x, y)] * steps  # one resident batch, `steps` steps

    def make_net():
        conf = (NeuralNetConfiguration.builder()
                .seed(11).updater(Sgd(learning_rate=0.01))
                .weight_init("xavier").list()
                .layer(DenseLayer(n_out=hidden, activation="relu"))
                .layer(OutputLayer(n_out=n_classes, loss="mcxent"))
                .set_input_type(InputType.feed_forward(n_features))
                .build())
        return MultiLayerNetwork(conf).init()

    def steps_per_sec(cm):
        net = make_net()
        net.fit(batches[0])      # compile + warmup
        float(net._score)

        def timed():
            t0 = time.perf_counter()
            net.fit(batches, checkpoint_manager=cm)
            float(net._score)    # VALUE fetch forces the whole chain
            return time.perf_counter() - t0

        return steps / _best_of(timed)

    tmp = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        sps_off = steps_per_sec(None)
        cm_async = CheckpointManager(os.path.join(tmp, "async"),
                                     save_every_n_steps=every, keep_last=3,
                                     async_write=True)
        sps_async = steps_per_sec(cm_async)
        cm_async.flush()
        written = cm_async.saves_committed
        retained = cm_async.checkpoints()
        ckpt_bytes = retained[-1]["size"] if retained else 0
        cm_async.close()
        cm_sync = CheckpointManager(os.path.join(tmp, "sync"),
                                    save_every_n_steps=every, keep_last=3,
                                    async_write=False)
        sps_sync = steps_per_sec(cm_sync)
        cm_sync.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    def overhead(sps):
        return round((sps_off - sps) / sps_off * 100, 1)

    emit("checkpoint_async_train_steps_per_sec", sps_async, "steps/sec",
         "checkpoint",
         steps=steps, save_every_n_steps=every,
         steps_per_sec_off=round(sps_off, 1),
         steps_per_sec_sync=round(sps_sync, 1),
         overhead_async_pct=overhead(sps_async),
         overhead_sync_pct=overhead(sps_sync),
         checkpoints_written=written, checkpoints_retained=len(retained),
         ckpt_bytes=ckpt_bytes,
         note="same train loop, checkpointing off vs async vs sync every "
              f"{every} steps (snapshot+atomic journaled commit each save); "
              "acceptance: overhead_async_pct < 10. " + _REPS_NOTE)


def bench_resilience():
    """Fault-tolerance path costs, metrics only (no thresholds here: the
    9p filesystem's fsync jitter swings disk-backed numbers run to run —
    acceptance bars belong to quiet full runs, per the checkpoint bench's
    note): (1) restore_latest latency through the LocalFS vs the
    ObjectStore backend — the time a preempted worker spends between
    process-up and training-again; (2) serving hot-swap pause — the max
    inter-dispatch gap a ParallelInference client sees while a checkpoint
    swap lands, vs its median gap without one (the swap prepares params
    off the dispatch path and only the pointer swap holds the model lock,
    so the gap should stay near the ordinary dispatch cadence)."""
    import shutil
    import tempfile

    from deeplearning4j_tpu.checkpoint import (CheckpointManager,
                                               ObjectStoreBackend)
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    n_features, n_classes, hidden = 64, 10, 128
    rng = np.random.default_rng(7)
    x = rng.standard_normal((256, n_features)).astype(np.float32)
    y = np.eye(n_classes, dtype=np.float32)[
        rng.integers(0, n_classes, 256)]
    ds = DataSet(x, y)

    def make_net():
        conf = (NeuralNetConfiguration.builder()
                .seed(23).updater(Sgd(learning_rate=0.01))
                .weight_init("xavier").list()
                .layer(DenseLayer(n_out=hidden, activation="relu"))
                .layer(OutputLayer(n_out=n_classes, loss="mcxent"))
                .set_input_type(InputType.feed_forward(n_features))
                .build())
        return MultiLayerNetwork(conf).init()

    def restore_ms(cm):
        import jax

        def timed():
            t0 = time.perf_counter()
            m = cm.restore_latest()
            # materialize a param leaf before stopping the clock
            np.asarray(jax.tree_util.tree_leaves(m.params)[0])
            return time.perf_counter() - t0
        return _best_of(timed) * 1000.0

    # --- restore latency, local vs object store ---------------------------
    tmp = tempfile.mkdtemp(prefix="bench_resil_")
    try:
        net = make_net()
        net.fit(ds)
        cm_local = CheckpointManager(os.path.join(tmp, "local"),
                                     async_write=False)
        cm_obj = CheckpointManager(storage=ObjectStoreBackend(),
                                   async_write=False)
        for cm in (cm_local, cm_obj):
            for _ in range(3):
                net.fit(ds)
                cm.save(net)
        local_ms = restore_ms(cm_local)
        object_ms = restore_ms(cm_obj)
        cm_local.close()

        # --- serving hot-swap pause --------------------------------------
        import threading

        served = cm_obj.restore_latest(load_updater=False)
        pi = ParallelInference(served, inference_mode="sequential")
        pi.start_hot_swap(cm_obj)  # manual polls; no background thread
        req = x[:8]
        pi.warmup(req)
        gaps_plain, gaps_swap = [], []

        def drive(gaps, n, swap_at=None):
            # the swap runs on its own thread, like the real poller — the
            # client stream only feels the param-pointer swap's lock hold
            swapper = None
            last = time.perf_counter()
            for i in range(n):
                if i == swap_at:
                    swapper = threading.Thread(target=pi.poll_checkpoint)
                    swapper.start()
                np.asarray(pi.output(req))
                now = time.perf_counter()
                gaps.append(now - last)
                last = now
            if swapper is not None:
                swapper.join()

        n = 30 if QUICK else 150
        drive(gaps_plain, n)
        net.fit(ds)
        cm_obj.save(net)  # the newer checkpoint the swap run picks up
        drive(gaps_swap, n, swap_at=n // 2)
        assert pi.stats()["hot_swap"]["swaps"] == 1
        pi.shutdown()
        cm_obj.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    swap_max_ms = max(gaps_swap) * 1000.0
    emit("checkpoint_restore_latest_ms", local_ms, "ms", "resilience",
         restore_local_ms=round(local_ms, 2),
         restore_object_store_ms=round(object_ms, 2),
         note="restore_latest wall time, LocalFSBackend vs in-process "
              "ObjectStoreBackend (manifest walk + sha256 + zip + device "
              "placement; the object-store number isolates the non-disk "
              "cost). " + _REPS_NOTE)
    emit("serving_hot_swap_max_gap_ms", swap_max_ms, "ms", "resilience",
         swaps=pi.stats()["hot_swap"]["swaps"],
         served_step=pi.stats()["hot_swap"]["current_checkpoint_step"],
         gap_p50_plain_ms=round(float(np.percentile(gaps_plain, 50)) * 1000,
                                2),
         gap_max_plain_ms=round(max(gaps_plain) * 1000, 2),
         gap_p50_swap_ms=round(float(np.percentile(gaps_swap, 50)) * 1000,
                               2),
         note="max gap between consecutive served dispatches across a "
              "checkpoint hot-swap vs the same client loop without one; "
              "restore+placement runs off the dispatch path, only the "
              "param pointer swap blocks. metrics only — thresholds on "
              "quiet full runs.")


def bench_grad_compression():
    """Compressed gradient collectives (parallel/compress.py): step time +
    compression ratio + est. bytes-on-wire for dense vs threshold vs top-k
    vs int8 on the zoo LeNet CNN and the charRNN (tBPTT path). The ratio
    is ANALYTIC accounting of the wire format (what a cross-slice DCN
    all-reduce would move); the step time shows what the in-step encode/
    decode costs on top — on this CPU container both are metrics only per
    the 9p/bench-sensitivity note (the compute-cost story belongs to a
    quiet TPU run), but the byte-reduction ratio is shape-derived and
    stable anywhere. Also probes the isolated compression pass into the
    ``grad_compress_ms`` histogram (obs/)."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import LeNet, TextGenerationLSTM
    from deeplearning4j_tpu.parallel.compress import (
        Int8Compression, ThresholdCompression, TopKCompression,
        compression_stats, enable_grad_compression,
        measure_compression_overhead)

    if QUICK:
        steps, cnn_batch, vocab, rnn_batch, seq = 4, 8, 16, 4, 16
    else:
        steps, cnn_batch, vocab, rnn_batch, seq = 20, 64, 47, 32, 100
    rng = np.random.default_rng(5)

    def lenet_batches():
        x = rng.standard_normal((cnn_batch, 28 * 28)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, cnn_batch)]
        return [DataSet(x, y)] * steps

    def charrnn_batches():
        ids = rng.integers(0, vocab, (rnn_batch, seq))
        x = np.eye(vocab, dtype=np.float32)[ids]
        y = np.eye(vocab, dtype=np.float32)[
            rng.integers(0, vocab, (rnn_batch, seq))]
        # T > tbptt_fwd_length: fit() runs the per-window tBPTT step, the
        # compressed path for sequence models
        return [DataSet(x, y)]

    models = (
        ("lenet", lambda: LeNet(num_classes=10).init(), lenet_batches()),
        ("charrnn",
         lambda: TextGenerationLSTM(total_unique_characters=vocab,
                                    units=32 if QUICK else 256,
                                    tbptt_length=seq // 2).init(),
         charrnn_batches()),
    )
    schemes = (
        ("dense", None),
        ("threshold", ThresholdCompression()),  # the DEFAULT policy: the
        # acceptance ratio is measured exactly here
        ("topk", TopKCompression(ratio=0.01)),
        ("int8", Int8Compression()),
    )
    for model_name, make_net, batches in models:
        results = {}
        threshold_ratio = None
        for scheme_name, scheme in schemes:
            net = make_net()
            if scheme is not None:
                enable_grad_compression(net, scheme)
            net.fit(batches[:1])  # compile + warmup
            float(net._score)

            def timed(n=net):
                sw = Stopwatch().start()
                n.fit(batches)
                return sw.stop(sync=n._score)

            dt = _best_of(timed)
            entry = {"steps_per_sec": round(len(batches) *
                                            _windows_per_batch(net, batches)
                                            / dt, 1)}
            if scheme is not None:
                st = compression_stats(net)
                per_step_dense = st["dense_bytes"] / st["steps"]
                per_step_wire = st["wire_bytes"] / st["steps"]
                entry.update(
                    ratio=round(st["dense_bytes"] / max(st["wire_bytes"], 1.0),
                                1),
                    dense_kb_per_step=round(per_step_dense / 1024.0, 1),
                    wire_kb_per_step=round(per_step_wire / 1024.0, 1),
                    residual_norm=round(st["residual_norm"], 4))
                if "tau" in st:
                    entry["tau"] = round(st["tau"], 6)
                if scheme_name == "threshold":
                    threshold_ratio = entry["ratio"]
                    entry["grad_compress_ms"] = round(
                        measure_compression_overhead(net), 3)
            results[scheme_name] = entry
        emit(f"grad_compression_{model_name}_threshold_byte_reduction_x",
             float(threshold_ratio), "x", "compression",
             schemes=results,
             note="est. bytes-on-wire reduction of the DEFAULT threshold "
                  "policy (DL4J dual sparse/bitmap accounting) vs the "
                  "dense f32 all-reduce; per-scheme step rates are "
                  "metrics-only on this host per the 9p note — the "
                  "acceptance bar is the ratio (>= 4x). " + _REPS_NOTE)


def _windows_per_batch(net, batches) -> int:
    """Optimizer steps one DataSet triggers: tBPTT batches advance one
    step per window, everything else one per batch."""
    conf = net.conf
    if getattr(conf, "backprop_type", "standard") != "tbptt":
        return 1
    T = batches[0].features.shape[1]
    L = conf.tbptt_fwd_length
    return max(1, -(-T // L))


def bench_quantized_inference():
    """Post-training int8 quantization (quant/): fp32 vs BN-folded fp32 vs
    int8 serving dispatch on the zoo LeNet and a residual conv block —
    model bytes, per-dispatch p50/p99 and the int8-vs-fp32 accuracy delta.
    The byte reduction is shape-derived and stable anywhere; dispatch
    latencies are metrics-only on this host per the 9p/bench-sensitivity
    note (XLA:CPU has no int8 matmul fast path — the latency story belongs
    to an MXU run)."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.convolutional import ConvolutionLayer
    from deeplearning4j_tpu.nn.conf.graph import (ElementWiseVertex,
                                                  GraphBuilder)
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.nn.conf.normalization import BatchNormalization
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.perf.fusion import fold_bn
    from deeplearning4j_tpu.quant import (accuracy_delta, calibrate,
                                          param_bytes, quantize,
                                          quantized_layers)

    if QUICK:
        batch, dispatches, cal_batches, res_hw, res_ch = 8, 6, 2, 8, 8
    else:
        batch, dispatches, cal_batches, res_hw, res_ch = 64, 40, 8, 32, 32

    def resnet_block():
        """One residual conv block (conv-BN-relu ×2 + skip add), the
        fold_bn→int8 shape ResNet-family serving graphs are made of."""
        parent = NeuralNetConfiguration.builder()
        parent.seed(5).updater(Sgd(0.05)).weight_init("relu")
        g = GraphBuilder(parent)
        g.add_inputs("in")
        g.add_layer("c1", ConvolutionLayer(n_out=res_ch, kernel_size=(3, 3),
                                           convolution_mode="same",
                                           activation="identity",
                                           has_bias=False), "in")
        g.add_layer("b1", BatchNormalization(), "c1")
        g.add_layer("a1", ActivationLayer(activation="relu"), "b1")
        g.add_layer("c2", ConvolutionLayer(n_out=res_ch, kernel_size=(3, 3),
                                           convolution_mode="same",
                                           activation="identity",
                                           has_bias=False), "a1")
        g.add_layer("b2", BatchNormalization(), "c2")
        g.add_vertex("add", ElementWiseVertex(op="add"), "b2", "a1")
        g.add_layer("a2", ActivationLayer(activation="relu"), "add")
        g.add_layer("out", OutputLayer(n_out=10, loss="mcxent"), "a2")
        g.set_outputs("out")
        g.set_input_types(InputType.convolutional(res_hw, res_hw, res_ch))
        return ComputationGraph(g.build()).init()

    rng = np.random.default_rng(7)
    models = (
        ("lenet", lambda: LeNet(num_classes=10).init(), (28, 28, 1), 10),
        ("resnet_block", resnet_block, (res_hw, res_hw, res_ch), 10),
    )
    for model_name, make_net, shape, n_classes in models:
        net = make_net()
        data = [DataSet(
            rng.standard_normal((batch,) + shape).astype(np.float32),
            np.eye(n_classes, dtype=np.float32)[
                rng.integers(0, n_classes, batch)])
            for _ in range(cal_batches)]
        # a few steps of training separate the logits: the accuracy gate
        # then measures real disagreement, not coin-flips between the
        # near-tied outputs of a random init
        net.fit(data, num_epochs=2)
        record = calibrate(net, (d.features for d in data))
        qnet = quantize(net, record)
        variants = (("fp32", net), ("fold_bn", fold_bn(net)),
                    ("int8", qnet))
        x = data[0].features
        results = {}
        for tag, m in variants:
            m.output(x)  # compile outside the timed region
            lat = []
            for _ in range(dispatches):
                sw = Stopwatch().start()
                sw.stop(m.output(x))  # output() is a host array: synced
                lat.append(sw.seconds * 1000.0)
            results[tag] = {
                "model_bytes": param_bytes(m),
                "p50_ms": round(float(np.percentile(lat, 50)), 2),
                "p99_ms": round(float(np.percentile(lat, 99)), 2),
            }
        reduction = (results["fp32"]["model_bytes"]
                     / max(results["int8"]["model_bytes"], 1))
        gate = accuracy_delta(net, qnet, data)
        emit(f"quantized_inference_{model_name}_byte_reduction_x",
             float(reduction), "x", "quant",
             variants=results,
             quantized_layers=len(quantized_layers(qnet)),
             top1_delta=round(gate["top1_delta"], 4),
             top1_agreement=round(gate["top1_agreement"], 4),
             loss_delta_rel=round(gate["loss_delta_rel"], 5),
             batch=batch,
             note="int8 weights + f32 scales/biases vs the fp32 serving "
                  "graph; acceptance bar is >= 3x bytes with the accuracy "
                  "delta inside the <= 1% gate budget. Dispatch latencies "
                  "are metrics-only on this host per the 9p note. "
                  + _REPS_NOTE)


def bench_autotune():
    """HBM planner + compile-time autotuner (perf/planner.py,
    perf/autotune.py): tuned-vs-default step time and activation bytes on
    LeNet + ResNet50. For each model the autotuner searches batch/fusion/
    donation under a budget 25% below the unplanned residual set, then the
    DEFAULT and TUNED configurations train a few measured steps at the
    same batch size. Metrics only per the 9p note (XLA:CPU timings do not
    transfer); the activation-bytes column is shape-derived and stable
    anywhere — that is the planner's acceptance number."""
    import jax

    from deeplearning4j_tpu.models import LeNet, ResNet50
    from deeplearning4j_tpu.nn.memory import conf_memory_report
    from deeplearning4j_tpu.perf.autotune import autotune, build_network
    from deeplearning4j_tpu.perf.fusion import training_activation_bytes

    if QUICK:
        jobs = [("lenet", LeNet(num_classes=10).conf(), (4,), 2)]
    else:
        jobs = [
            ("lenet", LeNet(num_classes=10).conf(), (64, 128, 256), 10),
            ("resnet50",
             ResNet50(num_classes=1000, input_shape=(224, 224, 3)).conf(),
             (64, 128), 6),
        ]
    rng = np.random.default_rng(11)
    for name, conf, batch_sizes, steps in jobs:
        mb = min(batch_sizes)
        rep = conf_memory_report(conf, minibatch=mb, training_bytes=False)
        fixed = rep.total_param_bytes + rep.updater_state_bytes
        budget = fixed + int(
            0.75 * training_activation_bytes(conf, minibatch=mb))
        record = autotune(conf, batch_sizes=batch_sizes,
                          budget_bytes=budget,
                          donation=(True,), top_k=1, reps=1 if QUICK else 2)
        # report activation bytes AT THE RECORD'S batch size, for the
        # record's own tuned conf — the same configuration the timing
        # below runs (the budget above was set at mb, the search floor)
        from deeplearning4j_tpu.perf.autotune import apply_tuning
        b = record.batch_size
        base_bytes = int(training_activation_bytes(conf, minibatch=b))
        tuned_bytes = int(training_activation_bytes(
            apply_tuning(conf, record), minibatch=b))

        def steps_per_sec(net, b):
            it = (conf.input_type if hasattr(conf, "input_type")
                  else conf.input_types[0])
            shape = (b, it.height, it.width, it.channels)
            n_out = 1000 if name == "resnet50" else 10
            x = rng.standard_normal(shape).astype(np.float32)
            y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, b)]
            net.init(validate=False)
            from deeplearning4j_tpu.datasets.dataset import DataSet
            ds = DataSet(x, y)
            net.fit(ds)  # compile + warm outside the timed region
            def run():
                sw = Stopwatch().start()
                for _ in range(steps):
                    net.fit(ds)
                sw.stop(jax.block_until_ready(net._score))
                return sw.seconds
            return steps / _best_of(run)

        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        default_net = (MultiLayerNetwork(conf)
                       if hasattr(conf, "layers") else
                       ComputationGraph(conf))
        tuned_net = build_network(conf, record)
        sps_default = steps_per_sec(default_net, b)
        sps_tuned = steps_per_sec(tuned_net, b)
        emit(f"autotune_{name}_tuned_vs_default_step_x",
             sps_tuned / max(sps_default, 1e-9), "x", "autotune",
             batch=b, fusion=record.fusion,
             remat_layers=len(record.remat),
             candidates=record.candidates_searched,
             default_activation_bytes=base_bytes,
             tuned_activation_bytes=tuned_bytes,
             activation_reduction=round(1 - tuned_bytes / base_bytes, 3),
             budget_bytes=budget,
             buckets=list(record.buckets),
             note="tuned = autotune TuningRecord applied (fusion + remat "
                  "under a budget 25% below the unplanned residual set); "
                  "step timings metrics-only on this host per the 9p "
                  "note — activation bytes are shape-derived and are the "
                  "planner acceptance number. " + _REPS_NOTE)


def bench_elastic():
    """Elastic-training path costs, metrics only (no thresholds — the 9p
    filesystem's fsync jitter swings disk-backed numbers run to run;
    acceptance bars belong to quiet full runs): (1) sharded checkpoint
    save (one epoch-boundary commit: shard snapshot + put + journal);
    (2) reshard-on-restore latency — reassembling a 4-host shard set into
    a 1-process world, the work a shrunk fleet does before training
    resumes; (3) membership-transition pause — bump request → new
    generation adopted → checkpoint restored, the storage-rendezvous part
    of an elastic transition (the jax.distributed re-init a multi-process
    world adds on top is measured by the slow chaos tests)."""
    import jax

    from deeplearning4j_tpu.checkpoint import CheckpointManager, ObjectStoreBackend
    from deeplearning4j_tpu.checkpoint import sharded as shd
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.updaters import Adam
    from deeplearning4j_tpu.parallel.elastic import LeaseBoard, Rendezvous

    rng = np.random.default_rng(11)
    x = rng.standard_normal((256, 64)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 256)]
    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater(Adam(0.01)).weight_init("xavier").list()
            .layer(DenseLayer(n_out=256, activation="relu"))
            .layer(OutputLayer(n_out=10, loss="mcxent"))
            .set_input_type(InputType.feed_forward(64))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(DataSet(x, y))

    # --- sharded save + restore --------------------------------------
    cm = CheckpointManager(storage=ObjectStoreBackend(), sharded=True)

    def save_once():
        t0 = time.perf_counter()
        cm.save(net)  # sharded saves are synchronous (device_get inside)
        np.asarray(jax.tree_util.tree_leaves(net.params)[0])
        return time.perf_counter() - t0
    save_ms = _best_of(save_once) * 1000.0

    def restore_once():
        t0 = time.perf_counter()
        m = cm.restore_latest()
        np.asarray(jax.tree_util.tree_leaves(m.params)[0])
        return time.perf_counter() - t0
    restore_ms = _best_of(restore_once) * 1000.0

    # --- 4-host shard set -> 1-process world (reshard-on-restore) ----
    payloads = [shd.shard_zip_bytes(s, {"batch_in_epoch": 0})
                for s in shd.simulated_shard_snapshots(net, 4)]

    def reshard_once():
        t0 = time.perf_counter()
        m, _ = shd.restore_from_payloads(payloads)
        np.asarray(jax.tree_util.tree_leaves(m.params)[0])
        return time.perf_counter() - t0
    reshard_ms = _best_of(reshard_once) * 1000.0

    # --- membership transition pause (storage-rendezvous half) -------
    store = ObjectStoreBackend()
    board = LeaseBoard(store, "w00", ttl_s=2.0, heartbeat_s=0.5)
    rd = Rendezvous(store, board, join_timeout_s=30.0, poll_s=0.01)
    rd.propose_or_await(1, expected=1)
    gen = [1]

    def transition_once():
        t0 = time.perf_counter()
        rd.request_bump(gen[0], "bench")
        rd.propose_or_await(gen[0] + 1)
        m = cm.restore_latest()
        np.asarray(jax.tree_util.tree_leaves(m.params)[0])
        gen[0] += 1
        return time.perf_counter() - t0
    transition_ms = _best_of(transition_once) * 1000.0

    emit("elastic_sharded_save_ms", save_ms, "ms", "elastic",
         restore_ms=round(restore_ms, 2),
         note="one epoch-boundary sharded commit (snapshot + shard put + "
              "journal) and its restore, in-process object store. "
              + _REPS_NOTE)
    emit("elastic_reshard_restore_ms", reshard_ms, "ms", "elastic",
         num_shards=4,
         note="reassemble a 4-host shard set into a 1-process world "
              "(N->M reshard-on-restore) incl. model build + placement. "
              + _REPS_NOTE)
    emit("elastic_membership_transition_ms", transition_ms, "ms",
         "elastic",
         note="bump request -> next generation adopted -> checkpoint "
              "restored (storage-rendezvous half of an elastic "
              "transition; multi-process re-init cost rides on top). "
              "metrics only — thresholds on quiet full runs per the 9p "
              "note. " + _REPS_NOTE)


def bench_data_plane():
    """Streaming data plane costs, metrics only (9p note: the lease path
    here runs over the in-process object store, so the numbers isolate
    protocol overhead from disk jitter; thresholds belong to quiet full
    runs): (1) host ETL records/s — plain list iterator vs the sharded
    reader vs the sharded reader with leases + consumption ledger; (2)
    record-range lease claim latency; (3) data-wait fraction of a real
    fit loop over the sharded reader, with and without async prefetch,
    via the train.data_wait spans."""
    import jax

    from deeplearning4j_tpu.checkpoint import ObjectStoreBackend
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import (
        AsyncDataSetIterator, ListDataSetIterator)
    from deeplearning4j_tpu.datasets.sharded import (ShardedDataset,
                                                     ShardLeaseBoard)
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.obs import trace as obs_trace
    from deeplearning4j_tpu.optimize.updaters import Adam

    rng = np.random.default_rng(23)
    n = 4096 if QUICK else 65536
    batch = 256
    x = rng.standard_normal((n, 64)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]

    def drain(it):
        # pure host ETL, no device work to sync
        t0 = time.perf_counter()  # lint: disable=DLT003
        count = 0
        for ds in it:
            count += ds.num_examples()
        return count / (time.perf_counter() - t0)

    plain = ListDataSetIterator(DataSet(x, y), batch)
    plain_rps = max(drain(plain) for _ in range(REPS))
    sds = ShardedDataset(x, y, batch_size=batch, seed=5)
    reader_rps = max(drain(sds.reader()) for _ in range(REPS))
    store = ObjectStoreBackend()
    sds_leased = ShardedDataset(x, y, batch_size=batch, seed=5,
                                store=store, ledger=True, lease_batches=8)
    leased_rps = max(drain(sds_leased.reader()) for _ in range(REPS))
    emit("data_plane_records_per_sec", reader_rps, "records/sec",
         "data_plane", plain_iterator=round(plain_rps, 1),
         leased_ledgered=round(leased_rps, 1), batch=batch, records=n,
         note="host ETL drain of the sharded reader (shuffle plan + row "
              "gather); plain_iterator is the pre-sharding baseline, "
              "leased_ledgered adds the lease protocol + per-batch "
              "consumption ledger over an in-process object store. "
              + _REPS_NOTE)

    # --- lease-claim latency ------------------------------------------
    board = ShardLeaseBoard(ObjectStoreBackend(), "bench-worker",
                            ttl_s=30.0)
    claims = 64 if QUICK else 512

    def claim_all():
        # host-side storage protocol, no device work to sync
        t0 = time.perf_counter()  # lint: disable=DLT003
        for c in range(claims):
            board.claim(0, c, 0, 1)
        return (time.perf_counter() - t0) / claims
    claim_us = _best_of(claim_all) * 1e6
    emit("data_plane_lease_claim_us", claim_us, "us", "data_plane_claim",
         claims=claims,
         note="one record-range lease claim (conflict scan + put + "
              "read-back) on an in-process object store; real object "
              "stores add their RTT. " + _REPS_NOTE)

    # --- data-wait fraction of a real fit loop ------------------------
    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater(Adam(0.01)).weight_init("xavier").list()
            .layer(DenseLayer(n_out=256, activation="relu"))
            .layer(OutputLayer(n_out=10, loss="mcxent"))
            .set_input_type(InputType.feed_forward(64))
            .build())

    def wait_fraction(wrap):
        net = MultiLayerNetwork(conf).init()
        spans = []
        tracer = obs_trace.Tracer(enabled=True)
        tracer.add_sink(spans.append)
        old = obs_trace._global
        obs_trace._global = tracer
        try:
            reader = ShardedDataset(x, y, batch_size=batch, seed=5).reader()
            t0 = time.perf_counter()
            net.fit(wrap(reader), num_epochs=1)
            jax.block_until_ready(net.params)
            total_ms = (time.perf_counter() - t0) * 1000.0
        finally:
            obs_trace._global = old
        wait_ms = sum(r["dur_ms"] for r in spans
                      if r["kind"] == "span"
                      and r["name"] == "train.data_wait")
        return wait_ms / max(total_ms, 1e-9)
    frac_sync = wait_fraction(lambda r: r)
    frac_async = wait_fraction(AsyncDataSetIterator)
    emit("data_plane_data_wait_fraction", frac_sync * 100.0, "%",
         "data_plane_wait",
         async_prefetch_pct=round(frac_async * 100.0, 2),
         note="share of one fit epoch spent waiting on the sharded "
              "reader (train.data_wait spans / wall); async_prefetch_pct "
              "is the same loop under AsyncDataSetIterator. metrics "
              "only — thresholds on quiet full runs per the 9p note.")


def bench_data_lake():
    """Data lake tier costs (9p note: the emulator is loopback HTTP, so
    the numbers isolate protocol + (de)serialization + cache overhead
    from real WAN latency): (1) host ETL records/s — in-RAM sharded
    reader vs lazily-pulled shard files over the wire client, cold and
    through the warmed disk cache (+ its hit rate); (2) small-model
    ``restore_latest`` latency per storage tier — local FS, the cloud
    client over the emulator, and the disk-cached cloud stack on its
    second (warm) restore."""
    import shutil
    import tempfile

    from deeplearning4j_tpu.checkpoint import (CheckpointManager,
                                               LocalFSBackend,
                                               RetryingBackend)
    from deeplearning4j_tpu.checkpoint.cache import CachedBackend
    from deeplearning4j_tpu.checkpoint.cloud import CloudObjectBackend
    from deeplearning4j_tpu.checkpoint.emulator import ObjectStoreEmulator
    from deeplearning4j_tpu.datasets.records import (ShardFileSource,
                                                     write_shards)
    from deeplearning4j_tpu.datasets.sharded import ShardedDataset
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.updaters import Sgd

    rng = np.random.default_rng(31)
    n = 4096 if QUICK else 65536
    batch, per_shard = 256, 512
    x = rng.standard_normal((n, 64)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]

    def drain(sds):
        # pure host ETL + storage wire, no device work to sync
        t0 = time.perf_counter()  # lint: disable=DLT003
        count = 0
        for ds in sds.reader():
            count += ds.num_examples()
        return count / (time.perf_counter() - t0)

    ram_rps = max(drain(ShardedDataset(x, y, batch_size=batch, seed=5))
                  for _ in range(REPS))
    tmp = tempfile.mkdtemp(prefix="bench-lake-")
    with ObjectStoreEmulator(access_key="bench",
                             secret_key="bench-secret") as emu:
        client = RetryingBackend(
            CloudObjectBackend(emu.url, "lake", access_key="bench",
                               secret_key="bench-secret"),
            base_backoff_s=0.01, max_backoff_s=0.2)
        write_shards(client, "shards/", x, y, records_per_shard=per_shard)

        def lake_sds(store):
            return ShardedDataset(source=ShardFileSource(store, "shards/"),
                                  batch_size=batch, seed=5,
                                  max_resident_shards=4)
        cold_rps = max(drain(lake_sds(client)) for _ in range(REPS))
        cache = CachedBackend(client, os.path.join(tmp, "cache"),
                              max_bytes=1 << 30)
        drain(lake_sds(cache))          # fill pass
        cached_rps = max(drain(lake_sds(cache)) for _ in range(REPS))
        emit("data_lake_records_per_sec", cached_rps, "records/sec",
             "data_lake", ram_rps=round(ram_rps, 1),
             lake_cold_rps=round(cold_rps, 1),
             lake_cached_rps=round(cached_rps, 1),
             cache_hit_rate=round(cache.stats()["hit_rate"], 3),
             batch=batch, records=n, records_per_shard=per_shard,
             note="sharded-reader drain: in-RAM arrays vs shard files "
                  "pulled through the wire client (cold) vs the warmed "
                  "disk cache; loopback emulator per the 9p note. "
                  + _REPS_NOTE)

        # --- restore latency per storage tier -------------------------
        conf = (NeuralNetConfiguration.builder()
                .seed(3).updater(Sgd(learning_rate=0.05))
                .weight_init("xavier").list()
                .layer(DenseLayer(n_out=32, activation="tanh"))
                .layer(OutputLayer(n_out=10, loss="mcxent"))
                .set_input_type(InputType.feed_forward(64))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(ShardedDataset(x[:512], y[:512], batch_size=128,
                               seed=5).reader(), num_epochs=1)

        def restore_ms(storage):
            cm = CheckpointManager(storage=storage, async_write=False)
            cm.save(net)

            def timed():
                # host-side storage path; restore materializes on host
                t0 = time.perf_counter()  # lint: disable=DLT003
                assert cm.restore_latest() is not None
                return time.perf_counter() - t0
            best = _best_of(timed) * 1000.0
            cm.close()
            return best
        local_ms = restore_ms(LocalFSBackend(os.path.join(tmp, "ckpt")))
        emu_ms = restore_ms(RetryingBackend(
            CloudObjectBackend(emu.url, "ckpt", access_key="bench",
                               secret_key="bench-secret")))
        warm = CachedBackend(
            RetryingBackend(CloudObjectBackend(
                emu.url, "ckpt-warm", access_key="bench",
                secret_key="bench-secret")),
            os.path.join(tmp, "ckpt-cache"), max_bytes=1 << 30)
        cached_ms = restore_ms(warm)
        emit("data_lake_restore_ms", cached_ms, "ms", "data_lake_restore",
             local_fs_ms=round(local_ms, 1),
             emulator_ms=round(emu_ms, 1),
             cached_warm_ms=round(cached_ms, 1),
             note="CheckpointManager.restore_latest of one small model "
                  "per storage tier; the cached arm is the SECOND "
                  "restore (disk hits, zero wire reads). " + _REPS_NOTE)
    shutil.rmtree(tmp, ignore_errors=True)


def bench_retrieval():
    """Vector retrieval: device-batched QPS + recall@10 + index MB for
    the full compression ladder — brute / IVF / int8-IVF / int4 / PQ /
    IVF-PQ — vs the host-side VPTree, at 100k and 1M vectors (QUICK: one
    tiny corpus, smaller PQ codebooks). Metrics only on CPU per the 9p
    note; the VPTree comparison is capped at 100k vectors (a
    million-node host tree takes minutes to build and proves nothing new
    about the host baseline)."""
    from deeplearning4j_tpu.clustering.vptree import VPTree
    from deeplearning4j_tpu.retrieval import (BruteForceIndex, IVFIndex,
                                              IVFPQIndex, PQIndex,
                                              recall_at_k,
                                              synthetic_corpus)

    sizes = [(2_000, 32)] if QUICK else [(100_000, 64), (1_000_000, 64)]
    n_queries = 64 if QUICK else 1024
    batch = 64 if QUICK else 256
    k = 10
    # QUICK shrinks the codebooks (256-entry books on a 2k corpus spend
    # the whole smoke budget inside KMeans for no extra signal)
    ksub = 64 if QUICK else 256
    for n, d in sizes:
        V, Q = synthetic_corpus(n, d, n_clusters=max(16, n // 200),
                                seed=0, queries=n_queries)

        def qps_of(ix):
            ix.warmup(max_queries=batch, ks=(k,))

            def timed():
                sw = Stopwatch().start()
                outs = None
                for lo in range(0, n_queries, batch):
                    outs = ix.search(Q[lo:lo + batch], k)
                # search() already fetched to host; bare stop is synced
                del outs
                return sw.stop()
            return n_queries / _best_of(timed)

        indexes = {
            "brute": BruteForceIndex(V),
            "ivf": IVFIndex(V),
            "ivf_int8": IVFIndex(V, int8=True),
            "int4": BruteForceIndex(V, int4=True),
            "pq": PQIndex(V, M=8, ksub=ksub, rerank=16),
            "ivf_pq": IVFPQIndex(V, M=8, ksub=ksub, rerank=8),
        }
        exact = indexes["brute"]
        # host-tree baseline: per-query tree walks on one CPU thread.
        # Capped at 100k vectors (a million-node host tree takes minutes
        # to build); the metric is NAMED by the tree's actual corpus so
        # the 1M device numbers never masquerade as a 1M host baseline.
        n_tree = min(n, 100_000)
        tree = VPTree(V[:n_tree])
        n_tree_q = min(n_queries, 32)
        sw = Stopwatch().start()
        for row in Q[:n_tree_q]:
            tree.search(row, k)
        tree_qps = n_tree_q / sw.stop()
        emit(f"retrieval_vptree_host_{n_tree // 1000}k_qps", tree_qps,
             "queries/sec", "retrieval",
             note=f"host VPTree baseline over {n_tree} vectors "
                  "(single-thread per-query tree walk)")
        for name, ix in indexes.items():
            qps = qps_of(ix)
            rec = (1.0 if name == "brute"
                   else recall_at_k(ix, Q, k, exact=exact))
            extra = {}
            if n_tree == n:
                extra["speedup_vs_vptree"] = round(qps / tree_qps, 1)
            else:  # different corpus sizes: an apples-to-apples ratio
                extra[f"vs_vptree_{n_tree // 1000}k_corpus"] = \
                    round(qps / tree_qps, 1)
            emit(f"retrieval_{name}_{n // 1000}k_qps", qps,
                 "queries/sec", "retrieval",
                 recall_at_10=round(rec, 4),
                 index_mb=round(ix.nbytes() / 1e6, 2),
                 note="device-batched top-k, batch "
                      f"{batch}, warmed pow2 ladder. " + _REPS_NOTE,
                 **extra)


def bench_pallas():
    """Pallas kernel on/off ablation (perf/pallas/): the hand-written
    kernels behind the fused BN-train custom-VJP and the retrieval
    ADC/int4 hot loops vs their XLA references. Three probes: (1) a bf16
    residual-block BN fwd+bwd micro-step; (2) the fused ResNet50 train
    step plus the jaxpr-derived training-activation-bytes each arm hands
    its backward (the HBM-traffic number the BN family attacks — the
    ~4.7 activation-set crossings of tools/PROFILE_r5.md); (3) retrieval
    QPS for the PQ / IVF-PQ / brute-int4 indexes. Off-TPU the "on" arm
    runs the kernels in Pallas interpret mode, so CPU numbers validate
    the plumbing and the metric shape, not the speedup — TPU rounds
    record the real deltas. QUICK skips the ResNet50 execution probe
    (interpret-mode compile of ~50 gridded BN kernels buys no smoke
    signal) but still emits both activation-byte lines."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models import ResNet50
    from deeplearning4j_tpu.nn.conf.convolutional import fused_bn_act_train
    from deeplearning4j_tpu.perf import pallas as _pk
    from deeplearning4j_tpu.perf.fusion import training_activation_bytes
    from deeplearning4j_tpu.retrieval import (BruteForceIndex, IVFPQIndex,
                                              PQIndex, synthetic_corpus)

    arms = ((False, "off"), (True, "on"))
    mode = "interpret" if _pk.interpret() else "native"

    # ---- probe 1: residual-block BN fwd+bwd micro-step (bf16) ----------
    if QUICK:
        n, side, c, steps = 4, 8, 32, 2
    else:
        n, side, c, steps = 32, 56, 128, 20
    rng = np.random.default_rng(0)
    z = jnp.asarray(rng.standard_normal((n, side, side, c), np.float32),
                    jnp.bfloat16)
    resid = jnp.asarray(rng.standard_normal((n, side, side, c), np.float32),
                        jnp.bfloat16)
    gamma = jnp.ones((c,), jnp.float32)
    beta = jnp.zeros((c,), jnp.float32)

    def _loss(z, gamma, beta, resid):
        out, _, _ = fused_bn_act_train("relu", 1e-5, z, gamma, beta, resid)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    bn_ms = {}
    for flag, tag in arms:
        with _pk.override(enabled=flag):
            # fresh jit per arm: kernel selection happens at trace time
            step = jax.jit(jax.grad(_loss, argnums=(0, 1, 2, 3)))
            jax.block_until_ready(step(z, gamma, beta, resid))

            def timed():
                sw = Stopwatch().start()
                for _ in range(steps):
                    grads = step(z, gamma, beta, resid)
                jax.block_until_ready(grads)
                return sw.stop()

            bn_ms[tag] = _best_of(timed) / steps * 1e3
        extra = {}
        if tag == "on":
            extra["speedup_vs_off"] = round(bn_ms["off"] / bn_ms["on"], 2)
        emit(f"pallas_bn_block_step_ms_{tag}", bn_ms[tag], "ms", "pallas",
             shape=[n, side, side, c], dtype="bfloat16", kernel_mode=mode,
             note="fused BN-train fwd+bwd over a residual block via the "
                  "fused_bn_act_train custom-VJP; on=Pallas kernels, "
                  "off=XLA reference. " + _REPS_NOTE, **extra)

    # ---- probe 2: fused ResNet50 step + activation-set bytes -----------
    if QUICK:
        batch, side2, warmup, steps2 = 2, 64, 1, 2
    else:
        batch = int(os.environ.get("BENCH_RESNET_BATCH", "128"))
        side2, warmup, steps2 = 224, 6, 30
    conf = _dc.replace(
        ResNet50(num_classes=1000, input_shape=(side2, side2, 3)).conf(),
        dtype="bfloat16").fused()
    rn_imgs = {}
    for flag, tag in arms:
        with _pk.override(enabled=flag):
            try:
                act_bytes = int(training_activation_bytes(conf,
                                                          minibatch=batch))
            except Exception:
                act_bytes = None
            extra = {"training_activation_bytes": act_bytes}
            if QUICK:  # jaxpr-derived bytes only; no interpret-mode compile
                emit(f"pallas_resnet50_activation_bytes_{tag}",
                     float(act_bytes or 0), "bytes", "pallas", batch=batch,
                     kernel_mode=mode, note="QUICK: jaxpr-derived "
                     "activation-set bytes only; execution probe runs on "
                     "full (TPU) rounds.")
                continue
            rn_imgs[tag], _ = _bench_resnet50_once(
                "bfloat16", batch, side2, warmup, steps2, fused=True)
        if not QUICK:
            if tag == "on":
                extra["speedup_vs_off"] = round(
                    rn_imgs["on"] / rn_imgs["off"], 2)
            emit(f"pallas_resnet50_imgs_per_sec_{tag}", rn_imgs[tag],
                 "imgs/sec", "pallas", batch=batch, kernel_mode=mode,
                 note="fused ResNet50 train step, Pallas BN kernels on/off. "
                      + _REPS_NOTE, **extra)

    # ---- probe 3: retrieval ADC / int4 QPS -----------------------------
    if QUICK:
        n_vec, d, n_queries, batch3, ksub = 2_000, 32, 64, 64, 64
    else:
        n_vec, d, n_queries, batch3, ksub = 100_000, 64, 512, 128, 256
    k = 10
    V, Q = synthetic_corpus(n_vec, d, n_clusters=max(16, n_vec // 200),
                            seed=0, queries=n_queries)
    indexes = {
        "pq": PQIndex(V, M=8, ksub=ksub),
        "ivf_pq": IVFPQIndex(V, M=8, ksub=ksub),
        "int4": BruteForceIndex(V, int4=True),
    }
    for name, ix in indexes.items():
        qps = {}
        for flag, tag in arms:
            with _pk.override(enabled=flag):
                # per-arm warmup: each _KernelSelect arm is its own jitted
                # function, so this traces the arm actually being timed
                ix.warmup(max_queries=batch3, ks=(k,))

                def timed():
                    sw = Stopwatch().start()
                    for lo in range(0, n_queries, batch3):
                        ix.search(Q[lo:lo + batch3], k)
                    return sw.stop()  # search() fetches to host

                qps[tag] = n_queries / _best_of(timed)
            extra = {}
            if tag == "on":
                extra["speedup_vs_off"] = round(qps["on"] / qps["off"], 2)
            emit(f"pallas_retrieval_{name}_qps_{tag}", qps[tag],
                 "queries/sec", "pallas", corpus=n_vec, kernel_mode=mode,
                 note="ADC/int4 scoring kernels on/off; identical ids "
                      "asserted in tests/test_zz_pallas.py. " + _REPS_NOTE,
                 **extra)


def main():
    benches = [("lenet", bench_lenet), ("word2vec", bench_word2vec),
               ("charlstm", bench_graveslstm), ("serving", bench_serving),
               ("serving_load", bench_serving_load),
               ("decode", bench_decode),
               ("fleet", bench_fleet),
               ("checkpoint", bench_checkpoint),
               ("resilience", bench_resilience),
               ("elastic", bench_elastic),
               ("data_plane", bench_data_plane),
               ("data_lake", bench_data_lake),
               ("retrieval", bench_retrieval),
               ("pallas", bench_pallas),
               ("grad_compression", bench_grad_compression),
               ("quantized_inference", bench_quantized_inference),
               ("autotune", bench_autotune),
               ("resnet50_fusion", bench_resnet50_fusion),
               ("resnet50", bench_resnet50)]
    only = os.environ.get("BENCH_ONLY")
    if only:
        wanted = {w.strip() for w in only.split(",") if w.strip()}
        unknown = wanted - {n for n, _ in benches}
        if unknown:
            raise SystemExit(f"BENCH_ONLY names unknown benches: "
                             f"{sorted(unknown)}")
        benches = [(n, f) for n, f in benches if n in wanted]
    for name, fn in benches:
        try:
            fn()
        except Exception as e:  # keep the remaining benches alive
            print(json.dumps({"metric": name, "error": f"{type(e).__name__}: {e}"}),
                  flush=True)


if __name__ == "__main__":
    main()
